"""The scheduler micro-benchmark matrix — scheduler_bench_test.go parity.

Reference: test/integration/scheduler_perf/scheduler_bench_test.go:32-52
runs BenchmarkScheduling{100,1000}Nodes{0,1000}Pods — measure scheduling
`measured` fresh pods onto a cluster of N nodes that already carries P
scheduled pods, reporting per-pod cost (the Go bench's ns/op).

Prints one JSON line per cell:
  {"cell": "100Nodes/0Pods", "nodes": 100, "preexisting": 0,
   "measured": 1000, "s_per_pod": ..., "pods_per_s": ...}
plus a trailing summary line with the full matrix, so the driver's
one-line-JSON readers and humans both get what they need.

Env knobs: MATRIX_CELLS="100:0,100:1000,1000:0,1000:1000" (nodes:pre),
MATRIX_MEASURED (default 1000, the upstream bench's fixed measurement
batch).
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   ".jax_cache"))
try:
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.environ["JAX_COMPILATION_CACHE_DIR"])
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:
    pass


def run_cell(n_nodes: int, n_pre: int, n_measured: int):
    """setupScheduler + the measured loop of benchmarkScheduling
    (scheduler_bench_test.go:57-95): preexisting pods are scheduled first
    and excluded from timing; the clock runs over the measured batch
    create -> all bound."""
    from kubernetes_tpu.engine.scheduler import Scheduler
    from kubernetes_tpu.models.hollow import PROFILES, hollow_nodes, \
        load_cluster
    from kubernetes_tpu.server.apiserver_lite import ApiServerLite

    api = ApiServerLite(max_log=max(200_000,
                                    3 * (n_nodes + n_pre + n_measured)))
    load_cluster(api, hollow_nodes(n_nodes), [])
    sched = Scheduler(api, record_events=False)
    sched.start()
    if n_pre:
        for p in PROFILES["density"](n_pre):
            api.create("Pod", p)
        totals = sched.run_until_drained()
        assert totals["bound"] == n_pre, totals
    measured = PROFILES["density"](n_measured)
    for p in measured:
        p.name = "measured-" + p.name
        api.create("Pod", p)
    t0 = time.monotonic()
    totals = sched.run_until_drained()
    elapsed = time.monotonic() - t0
    assert totals["bound"] == n_measured, totals
    return elapsed


def main() -> int:
    cells = os.environ.get("MATRIX_CELLS",
                           "100:0,100:1000,1000:0,1000:1000")
    n_measured = int(os.environ.get("MATRIX_MEASURED", "1000"))
    matrix = []
    for spec in cells.split(","):
        n_nodes, n_pre = (int(x) for x in spec.strip().split(":"))
        # warmup pass compiles the kernels for this cell's exact shape
        # bucket — a smaller warmup batch lands in a different bucket and
        # the measured run pays the compile (observed: 68 vs 3700 pods/s)
        run_cell(n_nodes, n_pre, n_measured)
        elapsed = run_cell(n_nodes, n_pre, n_measured)
        cell = {
            "cell": f"{n_nodes}Nodes/{n_pre}Pods",
            "nodes": n_nodes,
            "preexisting": n_pre,
            "measured": n_measured,
            "s_per_pod": round(elapsed / n_measured, 9),
            "pods_per_s": round(n_measured / elapsed, 1),
        }
        matrix.append(cell)
        print(json.dumps(cell), flush=True)
    print(json.dumps({"metric": "scheduler micro-bench matrix "
                                "(scheduler_bench_test.go:32-52 shape)",
                      "unit": "s/pod", "matrix": matrix}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
