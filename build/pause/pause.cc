// pause: the container that holds a pod's network namespace.
//
// C++ equivalent of the reference's only in-tree native program
// (build/pause/pause.c, 51 lines): block until terminated, reaping any
// zombies re-parented onto us (we are PID 1 inside the pod sandbox).
//
// Build: `make pause` (build/Makefile) -> build/bin/pause

#include <csignal>
#include <cstdio>
#include <cstdlib>

#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

namespace {

void sigdown(int sig) {
  std::fprintf(stderr, "shutting down, got signal %d\n", sig);
  std::exit(0);
}

void sigreap(int) {
  // reap everything that exited; WNOHANG so we never block in the handler
  while (waitpid(-1, nullptr, WNOHANG) > 0) {
  }
}

}  // namespace

int main() {
  struct sigaction down = {};
  down.sa_handler = sigdown;
  struct sigaction reap = {};
  reap.sa_handler = sigreap;
  reap.sa_flags = SA_NOCLDSTOP;
  if (sigaction(SIGINT, &down, nullptr) < 0) return 1;
  if (sigaction(SIGTERM, &down, nullptr) < 0) return 2;
  if (sigaction(SIGCHLD, &reap, nullptr) < 0) return 3;
  for (;;) {
    pause();
  }
  return 42;  // unreachable (pause.c's "epic fail" exit)
}
