"""Controllers: the reconcile layer (SURVEY.md §1 L4).

Every controller follows the reference's informer->workqueue->sync pattern
(pkg/controller/*): event handlers enqueue keys, workers pop from a
rate-limited queue, sync(key) diffs desired (spec) against observed (status)
and issues API writes. Heavy per-cluster math stays out of this layer — the
TPU owns the pod x node hot loop; controllers are O(objects-touched) host
work.
"""

from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.controllers.manager import ControllerManager

__all__ = ["Controller", "ControllerManager"]
