"""CSR approving + signing controllers.

References: pkg/controller/certificates/{certificate_controller.go,
approver/sarapprove.go (1.7: cmd/gke-certificates-controller approval
logic), signer}. Approval policy mirrors the kubelet bootstrap flow: a CSR
for cn system:node:<name> with org system:nodes, requested by a bootstrap
identity (group system:bootstrappers) or by the node itself (renewal), is
auto-approved; everything else waits for manual approval. Signing issues
the HMAC identity record the CertAuthenticator trusts."""

from __future__ import annotations

from kubernetes_tpu.auth.authn import CertAuthenticator
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.server.apiserver_lite import ApiServerLite, NotFound

NODES_GROUP = "system:nodes"
BOOTSTRAP_GROUP = "system:bootstrappers"


class CSRApprovingController(Controller):
    name = "csrapproving"

    def __init__(self, api: ApiServerLite, factory: SharedInformerFactory,
                 record_events: bool = True):
        super().__init__(api, record_events=record_events)
        factory.informer("CertificateSigningRequest").add_event_handler(
            on_add=lambda o: self.enqueue(o.name),
            on_update=lambda o, n: self.enqueue(n.name))

    def sync(self, key: str) -> None:
        try:
            csr = self.api.get("CertificateSigningRequest", "", key)
        except NotFound:
            return
        if csr.approved or csr.denied:
            return
        is_node_cert = (csr.cn.startswith("system:node:")
                        and csr.orgs == [NODES_GROUP])
        requestor_ok = (BOOTSTRAP_GROUP in csr.groups
                        or csr.requestor == csr.cn)
        if is_node_cert and requestor_ok:
            csr.approved = True
            self.api.update("CertificateSigningRequest", csr,
                            expect_rv=csr.resource_version)
            self.event("CertificateSigningRequest", key, "Normal",
                       "Approved", "auto-approved kubelet certificate")


class CSRSigningController(Controller):
    name = "csrsigning"

    def __init__(self, api: ApiServerLite, factory: SharedInformerFactory,
                 ca: CertAuthenticator, record_events: bool = True):
        super().__init__(api, record_events=record_events)
        self.ca = ca
        factory.informer("CertificateSigningRequest").add_event_handler(
            on_add=lambda o: self.enqueue(o.name),
            on_update=lambda o, n: self.enqueue(n.name))

    def sync(self, key: str) -> None:
        try:
            csr = self.api.get("CertificateSigningRequest", "", key)
        except NotFound:
            return
        if not csr.approved or csr.certificate is not None:
            return
        csr.certificate = self.ca.sign(csr.cn, csr.orgs)
        self.api.update("CertificateSigningRequest", csr,
                        expect_rv=csr.resource_version)
