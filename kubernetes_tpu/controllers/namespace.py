"""Namespace lifecycle controller (pkg/controller/namespace).

Two-phase delete: delete_namespace() marks the Namespace Terminating (the
apiserver's finalizer-gated delete); the controller then deletes every
namespaced object in it and finally removes the Namespace itself
(namespaced_resources_deleter.go Delete).
"""

from __future__ import annotations

import dataclasses

from kubernetes_tpu.api.workloads import Namespace
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.server.apiserver_lite import ApiServerLite, Conflict, NotFound

# every namespaced kind the store can hold (the deleter's dynamic discovery
# equivalent — SURVEY.md §2.2 namespace controller)
NAMESPACED_KINDS = (
    "Pod", "Service", "Endpoints", "ReplicaSet", "ReplicationController",
    "Deployment", "Job", "DaemonSet", "StatefulSet",
    "PersistentVolumeClaim", "Event", "ResourceQuota", "LimitRange",
)


def delete_namespace(api: ApiServerLite, name: str) -> None:
    """The DELETE /namespaces/<name> behavior: flip to Terminating."""
    ns: Namespace = api.get("Namespace", "", name)
    if ns.phase != "Terminating":
        api.update("Namespace", dataclasses.replace(ns, phase="Terminating"),
                   expect_rv=ns.resource_version)


class NamespaceController(Controller):
    name = "namespace-controller"

    def __init__(self, api: ApiServerLite, factory: SharedInformerFactory,
                 record_events: bool = False):
        super().__init__(api, record_events=record_events)
        self.ns_informer = factory.informer("Namespace")
        self.ns_informer.add_event_handler(
            on_add=lambda o: self.enqueue(o.name),
            on_update=lambda old, new: self.enqueue(new.name))

    def sync(self, key: str) -> None:
        try:
            ns = self.api.get("Namespace", "", key)
        except NotFound:
            return
        if ns.phase != "Terminating":
            return
        remaining = 0
        for kind in NAMESPACED_KINDS:
            objs, _ = self.api.list(kind)
            for obj in objs:
                if getattr(obj, "namespace", None) == key:
                    remaining += 1
                    try:
                        self.api.delete(kind, key, obj.name)
                    except NotFound:
                        pass
        if remaining == 0:
            try:
                self.api.delete("Namespace", "", key)
            except NotFound:
                pass
        else:
            self.enqueue(key)  # re-check until empty
