"""StatefulSet controller — ordinal identity pods.

Mirrors pkg/controller/statefulset/stateful_set_control.go's ordered-ready
semantics: pods <name>-0 .. <name>-N-1; create ordinal i only once i-1 is
Running; scale down from the highest ordinal, one at a time. Each sync makes
one step; convergence via pod-status watch requeues.
"""

from __future__ import annotations

from kubernetes_tpu.api.workloads import stamp_pod
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.controllers.replicaset import owner_uid_of
from kubernetes_tpu.server.apiserver_lite import ApiServerLite, Conflict, NotFound
import dataclasses


class StatefulSetController(Controller):
    name = "statefulset-controller"

    def __init__(self, api: ApiServerLite, factory: SharedInformerFactory,
                 record_events: bool = True):
        super().__init__(api, record_events=record_events)
        self.ss_informer = factory.informer("StatefulSet")
        self.pod_informer = factory.informer("Pod")
        self.ss_informer.add_event_handler(
            on_add=lambda o: self.enqueue(o.key()),
            on_update=lambda old, new: self.enqueue(new.key()))
        self.pod_informer.add_event_handler(
            on_add=self._on_pod, on_update=lambda o, n: self._on_pod(n),
            on_delete=self._on_pod)

    def _on_pod(self, pod) -> None:
        if pod.owner_kind == "StatefulSet" and pod.owner_name:
            self.enqueue(f"{pod.namespace}/{pod.owner_name}")

    def sync(self, key: str) -> None:
        namespace, name = key.split("/", 1)
        try:
            ss = self.api.get("StatefulSet", namespace, name)
        except NotFound:
            return
        my_uid = owner_uid_of("StatefulSet", namespace, name)
        owned = {p.name: p for p in self.pod_informer.store.list()
                 if p.owner_uid == my_uid and not p.deleted}
        # walk ordinals in order; create the first hole and stop (ordered-ready)
        ready = 0
        for i in range(ss.replicas):
            pod_name = f"{ss.name}-{i}"
            pod = owned.get(pod_name)
            if pod is None:
                stamped = stamp_pod(ss.template, pod_name, namespace,
                                    "StatefulSet", name)
                try:
                    self.api.create("Pod", stamped)
                except Conflict:
                    pass
                break
            if pod.phase != "Running":
                break  # wait for this ordinal before advancing
            ready += 1
        # scale down: delete highest ordinal beyond replicas, one per sync
        extra = sorted((n for n in owned
                        if self._ordinal(ss.name, n) >= ss.replicas),
                       key=lambda n: -self._ordinal(ss.name, n))
        if extra:
            try:
                self.api.delete("Pod", namespace, extra[0])
            except NotFound:
                pass
        if ss.ready_replicas != ready:
            fresh = self.api.get("StatefulSet", namespace, name)
            self.api.update("StatefulSet",
                            dataclasses.replace(fresh, ready_replicas=ready),
                            expect_rv=fresh.resource_version)

    @staticmethod
    def _ordinal(base: str, pod_name: str) -> int:
        try:
            return int(pod_name.rsplit("-", 1)[1])
        except (IndexError, ValueError):
            return -1
