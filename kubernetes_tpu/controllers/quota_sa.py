"""ResourceQuota + ServiceAccount/token + TTL + bootstrap controllers.

References:
- pkg/controller/resourcequota/resource_quota_controller.go: full
  recalculation of quota status.used from live objects on a resync cadence
  and on deletes (replenishment).
- pkg/controller/serviceaccount/serviceaccounts_controller.go: ensure the
  'default' ServiceAccount in every active namespace;
  tokens_controller.go: mint a token Secret per ServiceAccount.
- pkg/controller/ttl/ttl_controller.go: annotate nodes with a TTL for
  kubelet secret/configmap caching, stepped by cluster size.
- pkg/controller/bootstrap/{bootstrapsigner,tokencleaner}.go: sign the
  cluster-info ConfigMap with bootstrap tokens; delete expired tokens.
"""

from __future__ import annotations

import hashlib
import hmac
import time
from typing import Dict, Optional

from kubernetes_tpu.api.cluster import Secret, ServiceAccount
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.quota import quota_scopes_match, usage_for, add_usage
from kubernetes_tpu.server.apiserver_lite import ApiServerLite, Conflict, NotFound

QUOTA_KINDS = ("Pod", "Service", "ReplicationController", "Secret",
               "ConfigMap", "PersistentVolumeClaim")


class ResourceQuotaController(Controller):
    """Recompute status.used for each quota from live objects — the
    reconciliation that heals drift from the admission plugin's optimistic
    increments (resource_quota_controller.go syncResourceQuota)."""

    name = "resourcequota-controller"

    def __init__(self, api: ApiServerLite, factory: SharedInformerFactory,
                 record_events: bool = True):
        super().__init__(api, record_events=record_events)
        self.informer = factory.informer("ResourceQuota")
        self.informer.add_event_handler(
            on_add=lambda o: self.enqueue(o.namespace + "/" + o.name),
            on_update=lambda o, n: self.enqueue(n.namespace + "/" + n.name))
        # replenishment: object churn of quota-tracked kinds requeues the
        # namespace's quotas (replenishment_controller.go watches deletes;
        # adds are watched too so usage heals promptly even for writes that
        # bypassed the admission plugin's optimistic increment)
        for kind in QUOTA_KINDS:
            factory.informer(kind).add_event_handler(
                on_add=lambda o, _k=kind: self._replenish(o),
                on_delete=lambda o, _k=kind: self._replenish(o))

    def _replenish(self, obj) -> None:
        ns = getattr(obj, "namespace", "")
        for q in self.informer.store.list():
            if q.namespace == ns:
                self.enqueue(q.namespace + "/" + q.name)

    def resync_all(self) -> None:
        for q in self.api.list("ResourceQuota")[0]:
            self.enqueue(q.namespace + "/" + q.name)

    def sync(self, key: str) -> None:
        namespace, name = key.split("/", 1)
        try:
            quota = self.api.get("ResourceQuota", namespace, name)
        except NotFound:
            return
        used: Dict[str, int] = {}
        for kind in QUOTA_KINDS:
            for obj in self.api.list(kind)[0]:
                if getattr(obj, "namespace", "") != namespace:
                    continue
                if not quota_scopes_match(quota.scopes, kind, obj):
                    continue
                add_usage(used, usage_for(kind, obj))
        tracked = {k: used.get(k, 0) for k in quota.hard}
        if tracked != quota.used:
            quota.used = tracked
            self.api.update("ResourceQuota", quota,
                            expect_rv=quota.resource_version)


class ServiceAccountController(Controller):
    """Ensure 'default' SA per active namespace + a token Secret per SA
    (serviceaccounts_controller.go + tokens_controller.go)."""

    name = "serviceaccount-controller"
    TOKEN_SECRET_TYPE = "kubernetes.io/service-account-token"

    def __init__(self, api: ApiServerLite, factory: SharedInformerFactory,
                 token_issuer=None, record_events: bool = True):
        super().__init__(api, record_events=record_events)
        self.token_issuer = token_issuer  # ServiceAccountTokenAuthenticator
        factory.informer("Namespace").add_event_handler(
            on_add=lambda o: self.enqueue("ns/" + o.name),
            on_update=lambda o, n: self.enqueue("ns/" + n.name))
        factory.informer("ServiceAccount").add_event_handler(
            on_add=lambda o: self.enqueue("sa/" + o.namespace + "/" + o.name),
            on_delete=lambda o: self.enqueue("ns/" + o.namespace))

    def sync(self, key: str) -> None:
        parts = key.split("/")
        if parts[0] == "ns":
            self._ensure_default_sa(parts[1])
        else:
            self._ensure_token(parts[1], parts[2])

    def _ensure_default_sa(self, ns_name: str) -> None:
        try:
            ns = self.api.get("Namespace", "", ns_name)
        except NotFound:
            return
        if ns.phase != "Active":
            return
        try:
            self.api.get("ServiceAccount", ns_name, "default")
        except NotFound:
            try:
                self.api.create("ServiceAccount",
                                ServiceAccount("default", namespace=ns_name,
                                               uid=f"{ns_name}/default"))
            except Conflict:
                pass
            self.enqueue("sa/" + ns_name + "/default")

    def _ensure_token(self, ns: str, name: str) -> None:
        try:
            sa = self.api.get("ServiceAccount", ns, name)
        except NotFound:
            return
        secret_name = f"{name}-token"
        if secret_name in sa.secrets:
            return
        token = self.token_issuer.issue(ns, name, uid=sa.uid) \
            if self.token_issuer else f"fake-token-{ns}-{name}"
        try:
            self.api.create("Secret", Secret(
                secret_name, namespace=ns, type=self.TOKEN_SECRET_TYPE,
                data={"token": token, "namespace": ns},
                annotations={"kubernetes.io/service-account.name": name}))
        except Conflict:
            pass
        sa.secrets = list(sa.secrets) + [secret_name]
        self.api.update("ServiceAccount", sa, expect_rv=sa.resource_version)


class TTLController(Controller):
    """Node TTL annotation stepped by cluster size (ttl_controller.go
    ttlBoundaries: 0s <=100 nodes, 15s <=500, 30s <=1000, 60s <=2000,
    300s above)."""

    name = "ttl-controller"
    ANNOTATION = "node.alpha.kubernetes.io/ttl"
    BOUNDARIES = ((100, 0), (500, 15), (1000, 30), (2000, 60))

    def __init__(self, api: ApiServerLite, factory: SharedInformerFactory,
                 record_events: bool = True):
        super().__init__(api, record_events=record_events)
        self.informer = factory.informer("Node")
        self.informer.add_event_handler(
            on_add=lambda o: self.enqueue(o.name),
            on_update=lambda o, n: self.enqueue(n.name))

    def desired_ttl(self, n_nodes: int) -> int:
        for bound, ttl in self.BOUNDARIES:
            if n_nodes <= bound:
                return ttl
        return 300

    def sync(self, key: str) -> None:
        try:
            node = self.api.get("Node", "", key)
        except NotFound:
            return
        want = str(self.desired_ttl(len(self.informer.store)))
        if node.annotations.get(self.ANNOTATION) != want:
            node.annotations[self.ANNOTATION] = want
            self.api.update("Node", node, expect_rv=node.resource_version)


class BootstrapSignerController(Controller):
    """Sign the cluster-info ConfigMap with each bootstrap token
    (bootstrapsigner.go: jws-kubeconfig-<tokenID> HMAC entries)."""

    name = "bootstrap-signer"
    CLUSTER_INFO = "cluster-info"
    NS = "kube-public"

    def __init__(self, api: ApiServerLite, factory: SharedInformerFactory,
                 record_events: bool = True):
        super().__init__(api, record_events=record_events)
        factory.informer("Secret").add_event_handler(
            on_add=lambda o: self.enqueue("sign"),
            on_update=lambda o, n: self.enqueue("sign"),
            on_delete=lambda o: self.enqueue("sign"))
        factory.informer("ConfigMap").add_event_handler(
            on_update=lambda o, n: self.enqueue("sign"))

    def sync(self, key: str) -> None:
        try:
            cm = self.api.get("ConfigMap", self.NS, self.CLUSTER_INFO)
        except NotFound:
            return
        kubeconfig = cm.data.get("kubeconfig", "")
        want = {k: v for k, v in cm.data.items()
                if not k.startswith("jws-kubeconfig-")}
        for s in self.api.list("Secret")[0]:
            if s.type != "bootstrap.kubernetes.io/token":
                continue
            tid = s.data.get("token-id", "")
            tsecret = s.data.get("token-secret", "")
            if not tid or not tsecret:
                continue
            sig = hmac.new((tid + "." + tsecret).encode(),
                           kubeconfig.encode(), hashlib.sha256).hexdigest()
            want["jws-kubeconfig-" + tid] = sig
        if want != cm.data:
            cm.data = want
            self.api.update("ConfigMap", cm, expect_rv=cm.resource_version)


class TokenCleanerController(Controller):
    """Delete expired bootstrap token secrets (tokencleaner.go)."""

    name = "token-cleaner"

    def __init__(self, api: ApiServerLite, factory: SharedInformerFactory,
                 record_events: bool = True, now=time.time):
        super().__init__(api, record_events=record_events)
        self._now = now
        factory.informer("Secret").add_event_handler(
            on_add=lambda o: self.enqueue(o.namespace + "/" + o.name),
            on_update=lambda o, n: self.enqueue(n.namespace + "/" + n.name))

    def sync(self, key: str) -> None:
        namespace, name = key.split("/", 1)
        try:
            s = self.api.get("Secret", namespace, name)
        except NotFound:
            return
        if s.type != "bootstrap.kubernetes.io/token":
            return
        exp = s.data.get("expiration", "")
        if exp and float(exp) <= self._now():
            self.api.delete("Secret", namespace, name)
