"""Garbage collector + pod GC.

GarbageCollector: ownerRef-based cascade deletion
(pkg/controller/garbagecollector): an object whose controllerRef points at a
no-longer-existing owner is deleted. The reference builds a full dependency
graph from every resource; here the ownership DAG is two levels deep by
construction (Deployment -> ReplicaSet -> Pod; {RC,Job,DaemonSet,StatefulSet}
-> Pod), so the scan is direct.

PodGCController (pkg/controller/podgc/gc_controller.go): reaps terminated
pods beyond a threshold (oldest first) and pods bound to nodes that no
longer exist.
"""

from __future__ import annotations

from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.server.apiserver_lite import ApiServerLite, NotFound

# gc_controller.go terminatedPodGCThreshold default (12500 in kube-controller-
# manager options); tests override
DEFAULT_TERMINATED_POD_THRESHOLD = 12500

_OWNER_KINDS = ("ReplicaSet", "ReplicationController", "Job", "DaemonSet",
                "StatefulSet", "Deployment")


class GarbageCollector(Controller):
    """Keys are "<kind>/<ns>/<name>" of a *dependent* to re-check."""

    name = "garbage-collector"

    def __init__(self, api: ApiServerLite, factory: SharedInformerFactory,
                 record_events: bool = False):
        super().__init__(api, record_events=record_events)
        self.factory = factory
        self.pod_informer = factory.informer("Pod")
        self.rs_informer = factory.informer("ReplicaSet")
        for kind in _OWNER_KINDS:
            factory.informer(kind).add_event_handler(
                on_delete=lambda o, k=kind: self._on_owner_deleted(k, o))
        self.pod_informer.add_event_handler(
            on_add=lambda p: self._maybe_enqueue_pod(p))
        self.rs_informer.add_event_handler(
            on_add=lambda rs: self._maybe_enqueue_rs(rs))

    def _on_owner_deleted(self, kind: str, owner) -> None:
        ns = getattr(owner, "namespace", "")
        uid = f"{kind}/{ns}/{owner.name}"
        for p in self.pod_informer.store.list():
            if p.owner_uid == uid:
                self.enqueue(f"Pod/{p.namespace}/{p.name}")
        for rs in self.rs_informer.store.list():
            if rs.owner_kind == kind and rs.owner_name == owner.name \
                    and rs.namespace == ns:
                self.enqueue(f"ReplicaSet/{rs.namespace}/{rs.name}")

    def _maybe_enqueue_pod(self, pod) -> None:
        if pod.owner_kind:
            self.enqueue(f"Pod/{pod.namespace}/{pod.name}")

    def _maybe_enqueue_rs(self, rs) -> None:
        if rs.owner_kind:
            self.enqueue(f"ReplicaSet/{rs.namespace}/{rs.name}")

    def resync(self) -> None:
        """Full orphan scan (the reference's graph rebuild on sync)."""
        for p in self.pod_informer.store.list():
            if p.owner_kind:
                self.enqueue(f"Pod/{p.namespace}/{p.name}")
        for rs in self.rs_informer.store.list():
            if rs.owner_kind:
                self.enqueue(f"ReplicaSet/{rs.namespace}/{rs.name}")

    def sync(self, key: str) -> None:
        kind, namespace, name = key.split("/", 2)
        try:
            obj = self.api.get(kind, namespace, name)
        except NotFound:
            return
        owner_kind = getattr(obj, "owner_kind", "")
        owner_name = getattr(obj, "owner_name", "")
        if not owner_kind:
            return
        owner_ns = namespace  # owners are namespace-local
        try:
            self.api.get(owner_kind, owner_ns, owner_name)
        except NotFound:
            try:
                self.api.delete(kind, namespace, name)
            except NotFound:
                pass


class PodGCController(Controller):
    name = "podgc-controller"

    def __init__(self, api: ApiServerLite, factory: SharedInformerFactory,
                 terminated_threshold: int = DEFAULT_TERMINATED_POD_THRESHOLD,
                 record_events: bool = False):
        super().__init__(api, record_events=record_events)
        self.pod_informer = factory.informer("Pod")
        self.node_informer = factory.informer("Node")
        self.terminated_threshold = terminated_threshold

    def resync(self) -> None:
        self.enqueue("gc")  # single periodic work item (gc_controller.go gc())

    def sync(self, key: str) -> None:
        pods = self.pod_informer.store.list()
        # 1. terminated pods beyond the threshold, oldest (lowest rv) first
        terminated = sorted(
            (p for p in pods if p.phase in ("Succeeded", "Failed")),
            key=lambda p: p.resource_version)
        excess = len(terminated) - self.terminated_threshold
        for p in terminated[:max(0, excess)]:
            self._delete(p)
        # 2. pods bound to vanished nodes (gcOrphaned)
        node_names = {n.name for n in self.node_informer.store.list()}
        for p in pods:
            if p.node_name and p.node_name not in node_names:
                self._delete(p)

    def _delete(self, pod) -> None:
        try:
            self.api.delete("Pod", pod.namespace, pod.name)
        except NotFound:
            pass
