"""Cloud-facing controllers: service load balancers, routes, PV binding,
attach/detach.

References:
- pkg/controller/service/servicecontroller.go: type=LoadBalancer services
  get an LB ensured via the cloud provider; node-set changes update members;
  deletes tear the LB down.
- pkg/controller/route/routecontroller.go: one cloud route per node's
  podCIDR; stale routes removed.
- pkg/controller/volume/persistentvolume/pv_controller.go: bind pending
  PVCs to the smallest matching available PV (capacity + access modes),
  two-way binding annotations.
- pkg/controller/volume/attachdetach/attach_detach_controller.go: desired
  state = volumes of scheduled pods; attach missing, detach orphaned —
  recorded per node (the reference mutates node.status.volumesAttached).
"""

from __future__ import annotations

from typing import Dict, List, Set, Tuple

from kubernetes_tpu.api.types import VolumeKind
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.cloud import CloudProvider
from kubernetes_tpu.cloud.provider import Route
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.server.apiserver_lite import ApiServerLite, NotFound

ATTACHED_ANNOTATION = "volumes.kubernetes.io/attached"
# node.status.volumesInUse analog: devices some pod on the node has
# mounted — the kubelet publishes it (nodes/kubelet.py heartbeat), this
# controller refuses to detach them (attach_detach_controller.go honoring
# volumesInUse via the operation executor's VerifyVolumesAreAttached)
IN_USE_ANNOTATION = "volumes.kubernetes.io/in-use"
# volume kinds that require attach before mount (the attachable plugins:
# EBS/GCE-PD/AzureDisk/Cinder... — pkg/volume/*/attacher.go)
ATTACHABLE = {VolumeKind.AWS_EBS, VolumeKind.GCE_PD, VolumeKind.AZURE_DISK}


class ServiceLBController(Controller):
    name = "service-controller"

    def __init__(self, api: ApiServerLite, factory: SharedInformerFactory,
                 cloud: CloudProvider, record_events: bool = True):
        super().__init__(api, record_events=record_events)
        self.cloud = cloud
        self.node_informer = factory.informer("Node")
        factory.informer("Service").add_event_handler(
            on_add=lambda o: self.enqueue(o.key()),
            on_update=lambda o, n: self.enqueue(n.key()),
            on_delete=lambda o: self.enqueue(o.key()))
        self.node_informer.add_event_handler(
            on_add=lambda o: self._all_lbs(),
            on_delete=lambda o: self._all_lbs())

    def _all_lbs(self) -> None:
        for svc in self.api.list("Service")[0]:
            if svc.type == "LoadBalancer":
                self.enqueue(svc.key())

    def sync(self, key: str) -> None:
        if not self.cloud.has_load_balancer():
            return
        namespace, name = key.split("/", 1)
        try:
            svc = self.api.get("Service", namespace, name)
        except NotFound:
            self.cloud.ensure_load_balancer_deleted(key)
            return
        if svc.type != "LoadBalancer":
            if svc.load_balancer_ip:
                self.cloud.ensure_load_balancer_deleted(key)
                svc.load_balancer_ip = ""
                self.api.update("Service", svc, expect_rv=svc.resource_version)
            return
        nodes = [n.name for n in self.node_informer.store.list()
                 if n.is_ready() and not n.unschedulable]
        status = self.cloud.ensure_load_balancer(key, nodes)
        if svc.load_balancer_ip != status.ingress_ip:
            svc.load_balancer_ip = status.ingress_ip
            self.api.update("Service", svc, expect_rv=svc.resource_version)
            self.event("Service", key, "Normal", "EnsuredLoadBalancer",
                       f"Ensured load balancer {status.ingress_ip}")


class RouteController(Controller):
    name = "route-controller"

    def __init__(self, api: ApiServerLite, factory: SharedInformerFactory,
                 cloud: CloudProvider, record_events: bool = True):
        super().__init__(api, record_events=record_events)
        self.cloud = cloud
        self.node_informer = factory.informer("Node")
        self.node_informer.add_event_handler(
            on_add=lambda o: self.enqueue("reconcile"),
            on_update=lambda o, n: self.enqueue("reconcile"),
            on_delete=lambda o: self.enqueue("reconcile"))

    def sync(self, key: str) -> None:
        if not self.cloud.has_routes():
            return
        want: Dict[str, Tuple[str, str]] = {}
        for n in self.node_informer.store.list():
            if n.pod_cidr:
                want[n.name] = (n.name, n.pod_cidr)
        have = {r.target_node: r for r in self.cloud.list_routes()}
        for node_name, (target, cidr) in want.items():
            cur = have.get(node_name)
            if cur is None or cur.destination_cidr != cidr:
                self.cloud.create_route(Route(node_name, target, cidr))
        for node_name, r in have.items():
            if node_name not in want:
                self.cloud.delete_route(r.name)


CLASS_ANNOTATION = "volume.beta.kubernetes.io/storage-class"
PROVISIONED_BY_ANNOTATION = "pv.kubernetes.io/provisioned-by"
CLAIM_ANNOTATION = "pv.kubernetes.io/claim"
RECLAIM_ANNOTATION = "pv.kubernetes.io/reclaim-policy"

# provisioner name -> the volume-source kind the provisioned PV carries
_PROVISIONER_KINDS = {
    "kubernetes.io/gce-pd": VolumeKind.GCE_PD,
    "kubernetes.io/aws-ebs": VolumeKind.AWS_EBS,
    "kubernetes.io/azure-disk": VolumeKind.AZURE_DISK,
}


class PersistentVolumeBinder(Controller):
    """Bind unbound PVCs to available PVs: smallest PV whose capacity covers
    the claim (pv_controller.go findBestMatchForClaim ordering). Claims
    carrying a storage-class annotation bind only same-class PVs, and when
    none exists the class's provisioner dynamically creates one
    (pv_controller.go provisionClaim); on claim deletion a provisioned PV
    with reclaim policy Delete is removed (reclaimVolume)."""

    name = "persistentvolume-binder"

    def __init__(self, api: ApiServerLite, factory: SharedInformerFactory,
                 record_events: bool = True):
        super().__init__(api, record_events=record_events)
        factory.informer("PersistentVolumeClaim").add_event_handler(
            on_add=lambda o: self.enqueue(o.namespace + "/" + o.name),
            on_update=lambda o, n: self.enqueue(n.namespace + "/" + n.name),
            on_delete=lambda o: self.enqueue(
                "reclaim:" + o.namespace + "/" + o.name))
        factory.informer("PersistentVolume").add_event_handler(
            on_add=lambda o: self._requeue_pending(),
            on_update=lambda o, n: self._requeue_pending())

    def _requeue_pending(self) -> None:
        for pvc in self.api.list("PersistentVolumeClaim")[0]:
            if not pvc.volume_name:
                self.enqueue(pvc.namespace + "/" + pvc.name)

    def sync(self, key: str) -> None:
        if key.startswith("reclaim:"):
            return self._reclaim(key[len("reclaim:"):])
        namespace, name = key.split("/", 1)
        try:
            pvc = self.api.get("PersistentVolumeClaim", namespace, name)
        except NotFound:
            return
        if pvc.volume_name:
            return
        bound: Set[str] = {c.volume_name
                           for c in self.api.list("PersistentVolumeClaim")[0]
                           if c.volume_name}
        request = pvc.capacity
        want_modes = set(pvc.access_modes)
        want_class = getattr(pvc, "annotations", {}).get(
            CLASS_ANNOTATION, "")
        candidates = []
        for pv in self.api.list("PersistentVolume")[0]:
            if pv.name in bound:
                continue
            # class match: a classed claim binds only same-class PVs and
            # vice versa (pv_controller findMatchingVolume class check)
            if pv.annotations.get(CLASS_ANNOTATION, "") != want_class:
                continue
            # access modes: the PV must offer every mode the claim asks for
            # (pv_controller checkAccessModes)
            if want_modes and not want_modes.issubset(set(pv.access_modes)):
                continue
            if pv.capacity >= request:
                candidates.append((pv.capacity, pv.name))
        if not candidates:
            if want_class:
                self._provision(pvc, want_class)
            return
        candidates.sort()
        pvc.volume_name = candidates[0][1]
        self.api.update("PersistentVolumeClaim", pvc,
                        expect_rv=pvc.resource_version)
        self.event("PersistentVolumeClaim", key, "Normal", "Bound",
                   f"bound to {pvc.volume_name}")

    def _provision(self, pvc, class_name: str) -> None:
        """provisionClaim: the class's provisioner mints a PV sized to the
        request; it binds on the requeue its ADDED event triggers."""
        from kubernetes_tpu.api.types import PersistentVolume, Volume
        try:
            sc = self.api.get("StorageClass", "", class_name)
        except NotFound:
            self.event("PersistentVolumeClaim",
                       pvc.namespace + "/" + pvc.name, "Warning",
                       "ProvisioningFailed",
                       f'storageclass "{class_name}" not found')
            return
        kind = _PROVISIONER_KINDS.get(sc.provisioner)
        if kind is None:  # no-provisioner classes wait for manual PVs
            return
        import zlib
        claim_key = pvc.namespace + "/" + pvc.name
        # hashed name: "pvc-a-b"+"c" and "pvc-a"+"b-c" must not collide
        # (upstream avoids this with the claim UID)
        pv_name = (f"pvc-{zlib.adler32(claim_key.encode()) & 0xffffffff:08x}"
                   f"-{pvc.name[:40]}")
        try:
            existing = self.api.get("PersistentVolume", "", pv_name)
            if existing.annotations.get(CLAIM_ANNOTATION) == claim_key \
                    and existing.capacity >= pvc.capacity:
                return  # already provisioned; binding follows
            bound = {c.volume_name for c in self.api.list(
                "PersistentVolumeClaim")[0] if c.volume_name}
            if pv_name in bound:
                self.event("PersistentVolumeClaim", claim_key, "Warning",
                           "ProvisioningFailed",
                           f"volume {pv_name} exists and is bound "
                           f"elsewhere")
                return
            # stale (e.g. the claim was recreated larger): replace it
            self.api.delete("PersistentVolume", "", pv_name)
        except NotFound:
            pass
        self.api.create("PersistentVolume", PersistentVolume(
            pv_name, capacity=pvc.capacity,
            access_modes=list(pvc.access_modes),
            source=Volume(name=pv_name, kind=kind, volume_id=pv_name),
            annotations={
                CLASS_ANNOTATION: class_name,
                PROVISIONED_BY_ANNOTATION: sc.provisioner,
                CLAIM_ANNOTATION: pvc.namespace + "/" + pvc.name,
                RECLAIM_ANNOTATION: sc.reclaim_policy,
            }))
        self.event("PersistentVolumeClaim",
                   pvc.namespace + "/" + pvc.name, "Normal",
                   "ProvisioningSucceeded",
                   f"provisioned volume {pv_name}")

    def _reclaim(self, claim_key: str) -> None:
        """reclaimVolume: a dynamically provisioned PV whose claim is gone
        is deleted under reclaim policy Delete (Retain keeps it)."""
        live_bound = {c.volume_name for c in self.api.list(
            "PersistentVolumeClaim")[0] if c.volume_name}
        for pv in self.api.list("PersistentVolume")[0]:
            if pv.annotations.get(CLAIM_ANNOTATION) != claim_key:
                continue
            if pv.name in live_bound:
                # another (or a recreated) claim bound this PV between the
                # delete and this reclaim pass — deleting now would leave
                # a live claim dangling (pv_controller's bound/UID guard)
                continue
            if pv.annotations.get(RECLAIM_ANNOTATION, "Delete") == "Delete":
                try:
                    self.api.delete("PersistentVolume", "", pv.name)
                except NotFound:
                    pass
                self.event("PersistentVolume", pv.name, "Normal",
                           "VolumeDeleted", "reclaim policy Delete")


class AttachDetachController(Controller):
    """Reconcile attachable volumes to nodes hosting their pods; the
    attachment record is a node annotation (the reference writes
    node.status.volumesAttached)."""

    name = "attachdetach-controller"

    def __init__(self, api: ApiServerLite, factory: SharedInformerFactory,
                 record_events: bool = True, cloud=None):
        super().__init__(api, record_events=record_events)
        # optional cloud: real AttachDisk/DetachDisk calls ride along with
        # the node-annotation record (the reference's operation executor
        # calling the volume plugin attacher, which calls the cloud)
        self.cloud = cloud
        self.pod_informer = factory.informer("Pod")
        self.pod_informer.add_event_handler(
            on_add=lambda o: o.node_name and self.enqueue(o.node_name),
            on_update=lambda o, n: n.node_name and self.enqueue(n.node_name),
            on_delete=lambda o: o.node_name and self.enqueue(o.node_name))

    def sync(self, key: str) -> None:
        try:
            node = self.api.get("Node", "", key)
        except NotFound:
            return
        from kubernetes_tpu.volumes.plugins import VolumeError, resolve_spec
        want: Set[str] = set()
        for p in self.pod_informer.store.list():
            if p.node_name != key or p.deleted:
                continue
            for v in p.volumes:
                try:
                    # dereferences claim -> bound PV, like the desired-state
                    # populator's CreateVolumeSpec (attachdetach/cache/
                    # desired_state_of_world_populator.go)
                    src = resolve_spec(v, self.api, p.namespace).source
                except VolumeError:
                    continue  # missing/unbound claim: nothing to attach yet
                if VolumeKind(src.kind) in ATTACHABLE and src.volume_id:
                    want.add(str(VolumeKind(src.kind).value) + ":"
                             + src.volume_id)
        current = set(filter(None, node.annotations.get(
            ATTACHED_ANNOTATION, "").split(",")))
        # in-use protection: a device the kubelet still has mounted stays
        # attached even with no desiring pod (multi-attach corruption guard)
        in_use = set(filter(None, node.annotations.get(
            IN_USE_ANNOTATION, "").split(",")))
        want |= current & in_use
        attach_failures = []
        if want != current:
            if self.cloud is not None and self.cloud.has_disks():
                from kubernetes_tpu.cloud.provider import DiskError

                def vol_id(dev: str) -> str:
                    # tolerant of colon-less entries, like the volume
                    # plugins' Detacher parse
                    return dev.partition(":")[2] or dev

                for dev in sorted(want - current):
                    try:
                        self.cloud.attach_disk(vol_id(dev), key)
                    except DiskError as e:
                        # multi-attach / node limit: leave it un-recorded
                        # so the kubelet keeps waiting
                        self.event("Node", key, "Warning",
                                   "FailedAttachVolume", str(e))
                        attach_failures.append(str(e))
                        want.discard(dev)
                for dev in sorted(current - want):
                    self.cloud.detach_disk(vol_id(dev), key)
            if want != current:
                node.annotations[ATTACHED_ANNOTATION] = ",".join(sorted(want))
                self.api.update("Node", node,
                                expect_rv=node.resource_version)
        if attach_failures:
            # successful work above is committed; raising hands the key
            # back to the rate-limited queue so a refused attach is
            # RETRIED (the cloud state it lost to — e.g. a detach on the
            # other node — changes without any event landing on this one)
            raise RuntimeError(
                f"attach failures on {key}: " + "; ".join(attach_failures))
