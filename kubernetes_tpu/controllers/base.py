"""Controller base: informer handlers -> rate-limited key queue -> sync(key).

The shape of every pkg/controller/* worker loop (e.g. replica_set.go:151 Run,
processNextWorkItem): Get -> sync -> Forget on success / AddRateLimited on
error -> Done. Supports both threaded run(workers) and a deterministic pump()
for tests (the reference gets determinism the same way — calling syncHandler
directly in unit tests).
"""

from __future__ import annotations

import threading
from typing import List, Optional

from kubernetes_tpu.client.record import EventRecorder
from kubernetes_tpu.client.workqueue import (
    ItemExponentialFailureRateLimiter,
    RateLimitingQueue,
    ShutDown,
)
from kubernetes_tpu.server.apiserver_lite import ApiServerLite, Conflict, NotFound


class Controller:
    name = "controller"
    max_retries = 15  # replica_set.go statusUpdateRetries-ish bound for tests

    def __init__(self, api: ApiServerLite, record_events: bool = True):
        self.api = api
        self.queue = RateLimitingQueue(
            ItemExponentialFailureRateLimiter(base=0.005, max_delay=300.0))
        self.recorder: Optional[EventRecorder] = (
            EventRecorder(api, source=self.name) if record_events else None)
        self._threads: List[threading.Thread] = []
        self.sync_errors = 0

    # -------------------------------------------------------------- wiring

    def enqueue(self, key: str) -> None:
        self.queue.add(key)

    def sync(self, key: str) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def event(self, involved_kind: str, involved_key: str, etype: str,
              reason: str, message: str) -> None:
        if self.recorder is not None:
            self.recorder.event(involved_kind, involved_key, etype, reason, message)

    # ---------------------------------------------------------------- loop

    def process_one(self, timeout: float = 0.0) -> bool:
        try:
            key = self.queue.get(timeout)
        except (TimeoutError, ShutDown):
            return False
        try:
            self.sync(key)
        except (Conflict, NotFound):
            # optimistic-concurrency loss or racing delete: plain retry, the
            # informer will deliver fresher state (controller_utils.go pattern)
            self.sync_errors += 1
            if self.queue.num_requeues(key) < self.max_retries:
                self.queue.add_rate_limited(key)
            else:
                self.queue.forget(key)
        except Exception:
            self.sync_errors += 1
            if self.queue.num_requeues(key) < self.max_retries:
                self.queue.add_rate_limited(key)
            else:
                self.queue.forget(key)
        else:
            self.queue.forget(key)
        finally:
            self.queue.done(key)
        return True

    def pump(self, limit: int = 10_000) -> int:
        """Drain the queue synchronously (deterministic test mode)."""
        n = 0
        while n < limit and self.process_one():
            n += 1
        return n

    def run(self, workers: int = 1, poll: float = 0.05) -> None:
        for i in range(workers):
            t = threading.Thread(target=self._worker, args=(poll,),
                                 daemon=True, name=f"{self.name}-{i}")
            t.start()
            self._threads.append(t)

    def _worker(self, poll: float) -> None:
        while True:
            try:
                key = self.queue.get(None)
            except ShutDown:
                return
            try:
                self.sync(key)
            except Exception:
                self.sync_errors += 1
                if self.queue.num_requeues(key) < self.max_retries:
                    self.queue.add_rate_limited(key)
                else:
                    self.queue.forget(key)
            else:
                self.queue.forget(key)
            finally:
                self.queue.done(key)

    def stop(self) -> None:
        self.queue.shut_down()
        for t in self._threads:
            t.join(timeout=2.0)
