"""Deployment controller: declarative rollout over child ReplicaSets.

Mirrors pkg/controller/deployment (deployment_controller.go + rolling.go):
- each template revision gets a child RS named <deployment>-<template-hash>
  with the pod-template-hash label (deployment_util.go GetNewReplicaSet).
- RollingUpdate scales the new RS up within maxSurge and old RSes down within
  maxUnavailable, using ready counts as availability
  (rolling.go reconcileNewReplicaSet/reconcileOldReplicaSets).
- Recreate semantics fall out of max_surge=0, max_unavailable=replicas.
Each sync makes one step of progress; convergence comes from requeueing on
child RS status updates — the same level-triggered loop as the reference.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import List

from kubernetes_tpu.api.workloads import Deployment, ReplicaSet
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.server.apiserver_lite import ApiServerLite, Conflict, NotFound

TEMPLATE_HASH_LABEL = "pod-template-hash"


def template_hash(template) -> str:
    """Stable content hash of a pod template (fnv-of-spec analog,
    deployment_util.go GetPodTemplateSpecHash)."""
    blob = repr(dataclasses.asdict(template)).encode()
    return hashlib.sha1(blob).hexdigest()[:10]


class DeploymentController(Controller):
    name = "deployment-controller"

    def __init__(self, api: ApiServerLite, factory: SharedInformerFactory,
                 record_events: bool = True):
        super().__init__(api, record_events=record_events)
        self.factory = factory
        self.dep_informer = factory.informer("Deployment")
        self.rs_informer = factory.informer("ReplicaSet")
        self.dep_informer.add_event_handler(
            on_add=lambda o: self.enqueue(o.key()),
            on_update=lambda old, new: self.enqueue(new.key()),
            on_delete=lambda o: self.enqueue(o.key()))
        self.rs_informer.add_event_handler(
            on_add=self._on_rs, on_update=lambda o, n: self._on_rs(n),
            on_delete=self._on_rs)

    def _on_rs(self, rs: ReplicaSet) -> None:
        if rs.owner_kind == "Deployment" and rs.owner_name:
            self.enqueue(f"{rs.namespace}/{rs.owner_name}")

    # ----------------------------------------------------------------- sync

    def _children(self, dep: Deployment) -> List[ReplicaSet]:
        return [rs for rs in self.rs_informer.store.list()
                if rs.namespace == dep.namespace
                and rs.owner_kind == "Deployment" and rs.owner_name == dep.name]

    def sync(self, key: str) -> None:
        namespace, name = key.split("/", 1)
        try:
            dep = self.api.get("Deployment", namespace, name)
        except NotFound:
            return  # GC collects children
        if dep.paused:
            return
        want_hash = template_hash(dep.template)
        children = self._children(dep)
        new_rs = next((rs for rs in children
                       if rs.labels.get(TEMPLATE_HASH_LABEL) == want_hash), None)
        old_rses = [rs for rs in children if rs is not new_rs]

        if new_rs is None:
            new_rs = self._create_new_rs(dep, want_hash)
            if new_rs is None:
                return  # name conflict; watch event requeues

        self._reconcile_scale(dep, new_rs, old_rses)
        ready = sum(rs.ready_replicas for rs in self._children(dep))
        if (dep.updated_replicas != new_rs.ready_replicas
                or dep.ready_replicas != ready):
            fresh = self.api.get("Deployment", namespace, name)
            self.api.update("Deployment", dataclasses.replace(
                fresh, updated_replicas=new_rs.ready_replicas,
                ready_replicas=ready), expect_rv=fresh.resource_version)

    def _create_new_rs(self, dep: Deployment, want_hash: str):
        labels = dict(dep.template.labels)
        labels[TEMPLATE_HASH_LABEL] = want_hash
        template = dataclasses.replace(dep.template, labels=labels)
        selector = dataclasses.replace(
            dep.selector,
            match_labels={**dep.selector.match_labels,
                          TEMPLATE_HASH_LABEL: want_hash})
        rs = ReplicaSet(
            name=f"{dep.name}-{want_hash}", namespace=dep.namespace,
            labels=labels, replicas=0, selector=selector, template=template,
            owner_kind="Deployment", owner_name=dep.name)
        try:
            self.api.create("ReplicaSet", rs)
        except Conflict:
            return None
        fresh_dep = self.api.get("Deployment", dep.namespace, dep.name)
        self.api.update("Deployment",
                        dataclasses.replace(fresh_dep,
                                            revision=fresh_dep.revision + 1),
                        expect_rv=fresh_dep.resource_version)
        self.event("Deployment", dep.key(), "Normal", "ScalingReplicaSet",
                   f"Created new replica set {rs.name}")
        return self.api.get("ReplicaSet", rs.namespace, rs.name)

    def _reconcile_scale(self, dep: Deployment, new_rs: ReplicaSet,
                         old_rses: List[ReplicaSet]) -> None:
        total = new_rs.replicas + sum(rs.replicas for rs in old_rses)
        max_total = dep.replicas + dep.max_surge
        if new_rs.replicas > dep.replicas:
            # deployment was scaled down: shrink the new RS directly
            # (rolling.go reconcileNewReplicaSet's scale-down branch)
            self._scale_rs(new_rs, dep.replicas)
            return
        # scale new up within the surge budget (rolling.go:54)
        grow = min(dep.replicas - new_rs.replicas, max_total - total)
        if grow > 0:
            self._scale_rs(new_rs, new_rs.replicas + grow)
        # scale old down within the availability budget (rolling.go:87):
        # ready pods may drop to at most replicas - max_unavailable
        if old_rses:
            ready_total = new_rs.ready_replicas + sum(
                rs.ready_replicas for rs in old_rses)
            min_ready = dep.replicas - dep.max_unavailable
            budget = ready_total - min_ready
            # also shed any not-ready surplus on old RSes for free
            for rs in sorted(old_rses, key=lambda r: r.name):
                if rs.replicas == 0:
                    continue
                unready = rs.replicas - rs.ready_replicas
                shed = unready + max(0, min(budget, rs.ready_replicas))
                shed = min(shed, rs.replicas)
                if shed > 0:
                    budget -= max(0, shed - unready)
                    self._scale_rs(rs, rs.replicas - shed)

    def _scale_rs(self, rs: ReplicaSet, replicas: int) -> None:
        try:
            fresh = self.api.get("ReplicaSet", rs.namespace, rs.name)
            self.api.update("ReplicaSet",
                            dataclasses.replace(fresh, replicas=replicas),
                            expect_rv=fresh.resource_version)
        except (Conflict, NotFound):
            pass  # watch event will requeue the deployment
