"""ReplicaSet / ReplicationController reconciler.

Mirrors pkg/controller/replicaset/replica_set.go (and replication/, which the
reference implements as a thin fork of the same logic): syncReplicaSet diffs
spec.replicas against filtered live pods, then issues slow-start batched
creates or ranked deletes, then writes status. One class serves both kinds —
the only difference is the selector type (workloads.selector_of).

Adoption: matching orphan pods (no ownerRef) are claimed by stamping the
controllerRef, the PodControllerRefManager behavior
(pkg/controller/controller_ref_manager.go).
"""

from __future__ import annotations

import dataclasses
from typing import List

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.api.workloads import pods_matching, selector_of, stamp_pod
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.server.apiserver_lite import ApiServerLite, Conflict, NotFound

# controller_utils.go SlowStartInitialBatchSize
SLOW_START_INITIAL_BATCH = 1
# replica_set.go BurstReplicas
BURST_REPLICAS = 500


def _active(pod: Pod) -> bool:
    """controller.IsPodActive: not deleted, not terminated."""
    return not pod.deleted and pod.phase not in ("Succeeded", "Failed")


def _deletion_rank(pod: Pod) -> tuple:
    """ActivePods sort order (controller_utils.go:722 ActivePods.Less):
    prefer deleting unassigned, then pending, then not-running — i.e. the
    cheapest pods die first."""
    return (
        pod.node_name != "",        # unassigned first
        pod.phase != "Pending",     # pending before running
        pod.phase == "Running",     # running last
    )


def owner_uid_of(kind: str, namespace: str, name: str) -> str:
    return f"{kind}/{namespace}/{name}"


class ReplicaSetController(Controller):
    """Also serves ReplicationController via kind='ReplicationController'."""

    def __init__(self, api: ApiServerLite, factory: SharedInformerFactory,
                 kind: str = "ReplicaSet", record_events: bool = True):
        self.kind = kind
        self.name = kind.lower() + "-controller"
        super().__init__(api, record_events=record_events)
        self.factory = factory
        self.rs_informer = factory.informer(kind)
        self.pod_informer = factory.informer("Pod")
        self._suffix = 0
        self.rs_informer.add_event_handler(
            on_add=lambda o: self.enqueue(o.key()),
            on_update=lambda old, new: self.enqueue(new.key()),
            on_delete=lambda o: self.enqueue(o.key()))
        # pod events requeue the owning controller (addPod :228 / deletePod :345)
        self.pod_informer.add_event_handler(
            on_add=self._on_pod, on_update=lambda o, n: self._on_pod(n),
            on_delete=self._on_pod)

    def _on_pod(self, pod: Pod) -> None:
        if pod.owner_kind == self.kind and pod.owner_name:
            self.enqueue(f"{pod.namespace}/{pod.owner_name}")

    # ----------------------------------------------------------------- sync

    def sync(self, key: str) -> None:
        namespace, name = key.split("/", 1)
        try:
            rs = self.api.get(self.kind, namespace, name)
        except NotFound:
            return  # cascade deletion is the GC controller's job
        my_uid = owner_uid_of(self.kind, namespace, name)
        # Selector must select the template's pods, or every create is an
        # invisible orphan and the diff never closes -> unbounded creation.
        # The real apiserver rejects this at validation
        # (pkg/apis/extensions/validation ValidateReplicaSetSpec).
        effective_labels = rs.template.labels or dict(
            getattr(rs, "labels", {}) or {})
        if not selector_of(rs).matches(effective_labels):
            self.event(self.kind, rs.key(), "Warning", "SelectorMismatch",
                       "selector does not match pod template labels")
            return
        pods = pods_matching(rs, self.pod_informer.store.list())
        owned: List[Pod] = []
        for p in pods:
            if p.owner_uid == my_uid:
                owned.append(p)
            elif not p.owner_kind:  # adopt matching orphan
                claimed = dataclasses.replace(
                    p, owner_kind=self.kind, owner_name=name, owner_uid=my_uid)
                try:
                    self.api.update("Pod", claimed, expect_rv=p.resource_version)
                    owned.append(claimed)
                except (Conflict, NotFound):
                    pass  # retry via requeue on the watch event
        active = [p for p in owned if _active(p)]
        diff = rs.replicas - len(active)
        if diff > 0:
            self._create_pods(rs, min(diff, BURST_REPLICAS))
        elif diff < 0:
            self._delete_pods(active, -diff)
        # IsPodReady, not just phase: a Running pod failing its readiness
        # probe is not ready (replica_set.go calculateStatus)
        ready = sum(1 for p in active
                    if p.phase == "Running" and getattr(p, "ready", True))
        if rs.observed_replicas != len(active) or rs.ready_replicas != ready:
            fresh = self.api.get(self.kind, namespace, name)
            updated = dataclasses.replace(
                fresh, observed_replicas=len(active), ready_replicas=ready)
            self.api.update(self.kind, updated, expect_rv=fresh.resource_version)

    def _create_pods(self, rs, count: int) -> None:
        """Slow-start batching: 1, 2, 4, ... so a crash-looping template fails
        fast (controller_utils.go slowStartBatch)."""
        remaining = count
        batch = SLOW_START_INITIAL_BATCH
        while remaining > 0:
            n = min(batch, remaining)
            failures = 0
            for _ in range(n):
                if not self._create_one(rs):
                    failures += 1
            if failures:
                return  # stop the ramp; requeue comes from watch/backoff
            remaining -= n
            batch *= 2

    def _create_one(self, rs) -> bool:
        template = rs.template
        if not template.labels:
            template = dataclasses.replace(
                template, labels=dict(getattr(rs, "labels", {}) or {}))
        for _ in range(20):  # name collision retry
            self._suffix += 1
            pod_name = f"{rs.name}-{self._suffix:05d}"
            pod = stamp_pod(template, pod_name, rs.namespace,
                            self.kind, rs.name)
            try:
                self.api.create("Pod", pod)
                self.event(self.kind, rs.key(), "Normal", "SuccessfulCreate",
                           f"Created pod {pod_name}")
                return True
            except Conflict:
                continue
        return False

    def _delete_pods(self, active: List[Pod], count: int) -> None:
        victims = sorted(active, key=_deletion_rank)[:count]
        for p in victims:
            try:
                self.api.delete("Pod", p.namespace, p.name)
                self.event(self.kind, f"{p.namespace}/{p.owner_name}", "Normal",
                           "SuccessfulDelete", f"Deleted pod {p.name}")
            except NotFound:
                pass
