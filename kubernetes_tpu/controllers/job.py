"""Job controller — run pods to completion (pkg/controller/job/jobcontroller.go).

syncJob counts owned pods by phase: active (Pending/Running) backfill up to
min(parallelism, completions - succeeded); succeeded >= completions marks the
job complete and leaves terminated pods in place (the reference keeps them
for log retrieval; podgc reaps them past the threshold). Failures count
toward backoff_limit; past it the job stops creating pods.
"""

from __future__ import annotations

import dataclasses

from kubernetes_tpu.api.workloads import stamp_pod
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.controllers.replicaset import owner_uid_of
from kubernetes_tpu.server.apiserver_lite import ApiServerLite, Conflict, NotFound


class JobController(Controller):
    name = "job-controller"

    def __init__(self, api: ApiServerLite, factory: SharedInformerFactory,
                 record_events: bool = True):
        super().__init__(api, record_events=record_events)
        self.job_informer = factory.informer("Job")
        self.pod_informer = factory.informer("Pod")
        self._suffix = 0
        self.job_informer.add_event_handler(
            on_add=lambda o: self.enqueue(o.key()),
            on_update=lambda old, new: self.enqueue(new.key()))
        self.pod_informer.add_event_handler(
            on_add=self._on_pod, on_update=lambda o, n: self._on_pod(n),
            on_delete=self._on_pod)

    def _on_pod(self, pod) -> None:
        if pod.owner_kind == "Job" and pod.owner_name:
            self.enqueue(f"{pod.namespace}/{pod.owner_name}")

    def sync(self, key: str) -> None:
        namespace, name = key.split("/", 1)
        try:
            job = self.api.get("Job", namespace, name)
        except NotFound:
            return
        if job.complete:
            return
        my_uid = owner_uid_of("Job", namespace, name)
        owned = [p for p in self.pod_informer.store.list()
                 if p.owner_uid == my_uid and not p.deleted]
        active = sum(1 for p in owned if p.phase in ("Pending", "Running"))
        succeeded = sum(1 for p in owned if p.phase == "Succeeded")
        failed = sum(1 for p in owned if p.phase == "Failed")

        if succeeded < job.completions and failed <= job.backoff_limit:
            want_active = min(job.parallelism, job.completions - succeeded)
            for _ in range(max(0, want_active - active)):
                self._suffix += 1
                pod = stamp_pod(job.template, f"{job.name}-{self._suffix:05d}",
                                namespace, "Job", name)
                try:
                    self.api.create("Pod", pod)
                    active += 1
                except Conflict:
                    break
        complete = succeeded >= job.completions
        if (job.active, job.succeeded, job.failed, job.complete) != (
                active, succeeded, failed, complete):
            fresh = self.api.get("Job", namespace, name)
            self.api.update("Job", dataclasses.replace(
                fresh, active=active, succeeded=succeeded, failed=failed,
                complete=complete), expect_rv=fresh.resource_version)
            if complete and not job.complete:
                self.event("Job", job.key(), "Normal", "Completed",
                           f"Job completed ({succeeded}/{job.completions})")
