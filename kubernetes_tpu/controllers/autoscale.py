"""HPA + disruption (PDB) + cronjob controllers.

References:
- pkg/controller/podautoscaler/horizontal.go: desired = ceil(current *
  observed/target) with a 10% tolerance dead-band, clamped to [min,max];
  scale via the scale subresource.
- pkg/controller/disruption/disruption.go: PDB status — count healthy pods
  behind the selector, disruptionsAllowed = max(0, healthy - minAvailable).
- pkg/controller/cronjob/cronjob_controller.go: spawn Jobs on schedule,
  concurrency policies Allow/Forbid/Replace, history limits.
"""

from __future__ import annotations

import math
import time
from typing import Callable, Dict, List, Optional

from kubernetes_tpu.api.workloads import Job, pods_matching, stamp_pod
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.server.apiserver_lite import ApiServerLite, Conflict, NotFound


class StaticMetricsClient:
    """Test/bench metrics source: per-pod CPU usage in millicores.
    Stands in for heapster (the 1.7 metrics pipeline the HPA queried via
    pkg/controller/podautoscaler/metrics)."""

    def __init__(self):
        self.usage: Dict[str, int] = {}  # pod key -> mCPU used
        self.default = 0

    def pod_cpu_usage(self, pod) -> int:
        return self.usage.get(pod.key(), self.default)


class HorizontalPodAutoscalerController(Controller):
    name = "horizontal-pod-autoscaler"
    TOLERANCE = 0.1  # horizontal.go tolerance
    # scale-stabilization windows (horizontal.go upscaleForbiddenWindow 3m /
    # downscaleForbiddenWindow 5m) — without them the controller re-scales
    # against metrics gathered before the previous scale converged
    UPSCALE_WINDOW = 180.0
    DOWNSCALE_WINDOW = 300.0

    def __init__(self, api: ApiServerLite, factory: SharedInformerFactory,
                 metrics_client: Optional[StaticMetricsClient] = None,
                 record_events: bool = True, now=time.time):
        super().__init__(api, record_events=record_events)
        self.metrics = metrics_client or StaticMetricsClient()
        self._now = now
        self._last_scale: Dict[str, float] = {}
        self.pod_informer = factory.informer("Pod")
        factory.informer("HorizontalPodAutoscaler").add_event_handler(
            on_add=lambda o: self.enqueue(o.key()),
            on_update=lambda o, n: self.enqueue(n.key()))

    def resync_all(self) -> None:
        for h in self.api.list("HorizontalPodAutoscaler")[0]:
            self.enqueue(h.key())

    def sync(self, key: str) -> None:
        namespace, name = key.split("/", 1)
        try:
            hpa = self.api.get("HorizontalPodAutoscaler", namespace, name)
        except NotFound:
            return
        try:
            target = self.api.get(hpa.target_kind, namespace, hpa.target_name)
        except NotFound:
            return
        pods = pods_matching(target, self.pod_informer.store.list())
        current = target.replicas
        if not pods:
            desired = hpa.min_replicas
            utilization = 0
        else:
            used = sum(self.metrics.pod_cpu_usage(p) for p in pods)
            requested = sum(p.resource_request().milli_cpu for p in pods)
            if requested == 0:
                return  # horizontal.go: missing requests -> no decision
            utilization = int(round(100.0 * used / requested))
            ratio = utilization / max(hpa.target_cpu_utilization, 1)
            if abs(ratio - 1.0) <= self.TOLERANCE:
                desired = current
            else:
                desired = int(math.ceil(current * ratio))
        desired = max(hpa.min_replicas, min(hpa.max_replicas, desired))
        last = self._last_scale.get(key)
        now = self._now()
        window = self.UPSCALE_WINDOW if desired > current \
            else self.DOWNSCALE_WINDOW
        if desired != current and (last is None or now - last >= window):
            target.replicas = desired
            self.api.update(hpa.target_kind, target,
                            expect_rv=target.resource_version)
            self._last_scale[key] = now
        if (hpa.current_replicas, hpa.desired_replicas,
                hpa.current_cpu_utilization) != (current, desired, utilization):
            hpa.current_replicas = current
            hpa.desired_replicas = desired
            hpa.current_cpu_utilization = utilization
            self.api.update("HorizontalPodAutoscaler", hpa,
                            expect_rv=hpa.resource_version)


class DisruptionController(Controller):
    """Maintain PDB status from live pods (disruption.go updatePdbStatus)."""

    name = "disruption-controller"

    def __init__(self, api: ApiServerLite, factory: SharedInformerFactory,
                 record_events: bool = True):
        super().__init__(api, record_events=record_events)
        self.pod_informer = factory.informer("Pod")
        factory.informer("PodDisruptionBudget").add_event_handler(
            on_add=lambda o: self.enqueue(o.namespace + "/" + o.name),
            on_update=lambda o, n: self.enqueue(n.namespace + "/" + n.name))
        self.pod_informer.add_event_handler(
            on_add=self._on_pod, on_delete=self._on_pod,
            on_update=lambda o, n: self._on_pod(n))

    def _on_pod(self, pod) -> None:
        for pdb in self.api.list("PodDisruptionBudget")[0]:
            if pdb.namespace == pod.namespace and pdb.selector is not None \
                    and pdb.selector.matches(pod.labels):
                self.enqueue(pdb.namespace + "/" + pdb.name)

    def sync(self, key: str) -> None:
        namespace, name = key.split("/", 1)
        try:
            pdb = self.api.get("PodDisruptionBudget", namespace, name)
        except NotFound:
            return
        pods = pods_matching(pdb, self.pod_informer.store.list())
        healthy = sum(1 for p in pods if p.phase == "Running")
        expected = len(pods)
        allowed = max(0, healthy - pdb.min_available)
        status = (healthy, pdb.min_available, allowed, expected)
        if (pdb.current_healthy, pdb.desired_healthy,
                pdb.disruptions_allowed, pdb.expected_pods) != status:
            pdb.current_healthy = healthy
            pdb.desired_healthy = pdb.min_available
            pdb.disruptions_allowed = allowed
            pdb.expected_pods = expected
            self.api.update("PodDisruptionBudget", pdb,
                            expect_rv=pdb.resource_version)


def parse_schedule(spec: str) -> float:
    """Seconds between runs for the supported schedule forms:
    '@every Ns', '*/N * * * *' (every N minutes), 'M H * * *' (daily —
    interval approximation 86400s). The reference uses robfig/cron; the
    controller only needs the next-fire delta."""
    spec = spec.strip()
    if spec.startswith("@every "):
        v = spec[len("@every "):]
        if v.endswith("s"):
            return float(v[:-1])
        if v.endswith("m"):
            return float(v[:-1]) * 60
        if v.endswith("h"):
            return float(v[:-1]) * 3600
        return float(v)
    fields = spec.split()
    if len(fields) == 5:
        minute = fields[0]
        if minute.startswith("*/"):
            return float(minute[2:]) * 60
        if fields[1].startswith("*/"):
            return float(fields[1][2:]) * 3600
        if minute == "*" :
            return 60.0
        return 86400.0
    raise ValueError(f"unsupported schedule {spec!r}")


class CronJobController(Controller):
    """cronjob_controller.go syncOne: fire when now >= last + interval."""

    name = "cronjob-controller"

    def __init__(self, api: ApiServerLite, factory: SharedInformerFactory,
                 record_events: bool = True, now=time.time):
        super().__init__(api, record_events=record_events)
        self._now = now
        factory.informer("CronJob").add_event_handler(
            on_add=lambda o: self.enqueue(o.key()),
            on_update=lambda o, n: self.enqueue(n.key()))

    def tick(self) -> None:
        """Cadence entry (the reference polls every 10s — cronjob_controller
        .go Run's wait.Until)."""
        for cj in self.api.list("CronJob")[0]:
            self.enqueue(cj.key())

    def sync(self, key: str) -> None:
        namespace, name = key.split("/", 1)
        try:
            cj = self.api.get("CronJob", namespace, name)
        except NotFound:
            return
        if cj.suspend:
            return
        jobs = [j for j in self.api.list("Job")[0]
                if j.namespace == namespace
                and j.name.startswith(name + "-")]
        active = [j for j in jobs if not j.complete]
        finished = [j for j in jobs if j.complete]
        now = self._now()
        interval = parse_schedule(cj.schedule)
        changed = False
        if now - cj.last_schedule_time >= interval:
            if active and cj.concurrency_policy == "Forbid":
                pass  # skip this window (syncOne's Forbid branch)
            else:
                if active and cj.concurrency_policy == "Replace":
                    for j in active:
                        self.api.delete("Job", namespace, j.name)
                    active = []
                job = Job(
                    name=f"{name}-{int(now)}", namespace=namespace,
                    completions=cj.job_template.completions,
                    parallelism=cj.job_template.parallelism,
                    template=cj.job_template.template)
                try:
                    self.api.create("Job", job)
                except Conflict:
                    return
                cj.last_schedule_time = now
                active.append(job)
                changed = True
        # history limits (cleanup in syncOne)
        succeeded = sorted([j for j in finished if j.failed == 0],
                           key=lambda j: j.name)
        failed = sorted([j for j in finished if j.failed > 0],
                        key=lambda j: j.name)
        for j in succeeded[: max(0, len(succeeded)
                                 - cj.successful_jobs_history_limit)]:
            self.api.delete("Job", namespace, j.name)
        for j in failed[: max(0, len(failed) - cj.failed_jobs_history_limit)]:
            self.api.delete("Job", namespace, j.name)
        actives = sorted(j.name for j in active)
        if changed or actives != cj.active_jobs:
            cj.active_jobs = actives
            self.api.update("CronJob", cj, expect_rv=cj.resource_version)
