"""Endpoints controller — Service selector -> ready pod addresses.

Mirrors pkg/controller/endpoint/endpoints_controller.go: for each Service,
the Endpoints object lists addresses of Running, non-deleted, bound pods
matching the selector. kube-proxy-lite (models/hollow.py HollowProxy)
consumes these to program its routing table.
"""

from __future__ import annotations

import dataclasses
import hashlib

from kubernetes_tpu.api.workloads import Endpoints, EndpointAddress
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.server.apiserver_lite import ApiServerLite, Conflict, NotFound


def _pod_ip(pod_key: str) -> str:
    """Deterministic synthetic pod IP in 10/8 (stable across runs, unlike
    builtin hash() which is seed-randomized)."""
    h = hashlib.sha1(pod_key.encode()).digest()
    return f"10.{h[0]}.{h[1]}.{1 + h[2] % 254}"


class EndpointController(Controller):
    name = "endpoint-controller"

    def __init__(self, api: ApiServerLite, factory: SharedInformerFactory,
                 record_events: bool = True):
        super().__init__(api, record_events=record_events)
        self.svc_informer = factory.informer("Service")
        self.pod_informer = factory.informer("Pod")
        self.svc_informer.add_event_handler(
            on_add=lambda o: self.enqueue(o.key()),
            on_update=lambda old, new: self.enqueue(new.key()),
            on_delete=lambda o: self.enqueue(o.key()))
        self.pod_informer.add_event_handler(
            on_add=self._on_pod,
            # both old and new: a label change out of a selector must requeue
            # the service that used to select the pod
            on_update=lambda o, n: (self._on_pod(o), self._on_pod(n)),
            on_delete=self._on_pod)

    def _on_pod(self, pod) -> None:
        # requeue services selecting this pod (endpoints_controller.go getPodServices)
        for svc in self.svc_informer.store.list():
            if svc.namespace != pod.namespace or not svc.selector:
                continue
            if all(pod.labels.get(k) == v for k, v in svc.selector.items()):
                self.enqueue(svc.key())

    def sync(self, key: str) -> None:
        namespace, name = key.split("/", 1)
        try:
            svc = self.api.get("Service", namespace, name)
        except NotFound:
            try:
                self.api.delete("Endpoints", namespace, name)
            except NotFound:
                pass
            return
        addrs = []
        if svc.selector:
            for p in self.pod_informer.store.list():
                if (p.namespace == namespace and not p.deleted
                        and p.phase == "Running" and p.node_name
                        and getattr(p, "ready", True)  # readiness gating
                        # (endpoints_controller.go only lists Ready pods)
                        and all(p.labels.get(k) == v
                                for k, v in svc.selector.items())):
                    addrs.append(EndpointAddress(
                        pod_key=p.key(), node_name=p.node_name,
                        ip=_pod_ip(p.key())))
        addrs.sort(key=lambda a: a.pod_key)
        try:
            cur = self.api.get("Endpoints", namespace, name)
            if cur.addresses != addrs:
                self.api.update("Endpoints",
                                dataclasses.replace(cur, addresses=addrs),
                                expect_rv=cur.resource_version)
        except NotFound:
            self.api.create("Endpoints",
                            Endpoints(name=name, namespace=namespace,
                                      addresses=addrs))
