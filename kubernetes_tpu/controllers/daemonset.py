"""DaemonSet controller — one pod per eligible node.

Mirrors pkg/controller/daemon/daemoncontroller.go: nodeShouldRunDaemonPod
checks node readiness, unschedulable, the template's node selector, and
taint toleration; the controller writes pods with spec.nodeName set directly,
bypassing the scheduler (the 1.7 behavior — scheduled DaemonSets came later).
"""

from __future__ import annotations

import dataclasses

from kubernetes_tpu.api.types import Node, Pod, TaintEffect
from kubernetes_tpu.api.workloads import stamp_pod
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.controllers.replicaset import owner_uid_of
from kubernetes_tpu.server.apiserver_lite import ApiServerLite, Conflict, NotFound


def node_should_run(ds_template: Pod, node: Node) -> bool:
    """nodeShouldRunDaemonPod, reduced to the checks our model carries:
    Ready condition, unschedulable (DS tolerates it in 1.7 only via
    annotation — we require schedulable), node selector, NoSchedule/NoExecute
    taints vs template tolerations."""
    if not node.is_ready():
        return False
    for k, v in ds_template.node_selector.items():
        if node.labels.get(k) != v:
            return False
    for taint in node.taints:
        if taint.effect in (TaintEffect.NO_SCHEDULE, TaintEffect.NO_EXECUTE):
            if not any(t.tolerates(taint) for t in ds_template.tolerations):
                return False
    return True


class DaemonSetController(Controller):
    name = "daemonset-controller"

    def __init__(self, api: ApiServerLite, factory: SharedInformerFactory,
                 record_events: bool = True):
        super().__init__(api, record_events=record_events)
        self.ds_informer = factory.informer("DaemonSet")
        self.node_informer = factory.informer("Node")
        self.pod_informer = factory.informer("Pod")
        self.ds_informer.add_event_handler(
            on_add=lambda o: self.enqueue(o.key()),
            on_update=lambda old, new: self.enqueue(new.key()))
        # node add/change re-evaluates every DS (daemoncontroller.go addNode)
        self.node_informer.add_event_handler(
            on_add=lambda n: self._enqueue_all(),
            on_update=lambda o, n: self._enqueue_all(),
            on_delete=lambda n: self._enqueue_all())
        self.pod_informer.add_event_handler(
            on_delete=self._on_pod)

    def _enqueue_all(self) -> None:
        for ds in self.ds_informer.store.list():
            self.enqueue(ds.key())

    def _on_pod(self, pod) -> None:
        if pod.owner_kind == "DaemonSet" and pod.owner_name:
            self.enqueue(f"{pod.namespace}/{pod.owner_name}")

    def sync(self, key: str) -> None:
        namespace, name = key.split("/", 1)
        try:
            ds = self.api.get("DaemonSet", namespace, name)
        except NotFound:
            return
        my_uid = owner_uid_of("DaemonSet", namespace, name)
        by_node = {}
        for p in self.pod_informer.store.list():
            if p.owner_uid == my_uid and not p.deleted:
                by_node.setdefault(p.node_name, []).append(p)
        nodes = self.node_informer.store.list()
        desired = current = 0
        for node in nodes:
            should = node_should_run(ds.template, node)
            have = by_node.pop(node.name, [])
            if should:
                desired += 1
                if not have:
                    pod = stamp_pod(ds.template, f"{ds.name}-{node.name}",
                                    namespace, "DaemonSet", name)
                    pod = dataclasses.replace(pod, node_name=node.name)
                    try:
                        self.api.create("Pod", pod)
                        current += 1
                    except Conflict:
                        pass
                else:
                    current += 1
                    for extra in have[1:]:  # dedupe
                        self._delete(extra)
            else:
                for p in have:
                    self._delete(p)
        for orphaned in by_node.values():  # pods on vanished nodes
            for p in orphaned:
                self._delete(p)
        if (ds.desired_scheduled, ds.current_scheduled) != (desired, current):
            fresh = self.api.get("DaemonSet", namespace, name)
            self.api.update("DaemonSet", dataclasses.replace(
                fresh, desired_scheduled=desired, current_scheduled=current),
                expect_rv=fresh.resource_version)

    def _delete(self, pod) -> None:
        try:
            self.api.delete("Pod", pod.namespace, pod.name)
        except NotFound:
            pass
