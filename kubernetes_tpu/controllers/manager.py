"""Controller manager: runs every reconcile controller over one shared
informer factory, under leader election.

Mirrors cmd/kube-controller-manager/app/controllermanager.go:107 (Run with
leaderelection.RunOrDie) and the initializer map at :313-339. The node
lifecycle controller (failure detection) registers here too once constructed
(controllers/nodelifecycle.py).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.client.leaderelection import LeaderElector, LeaseLock
from kubernetes_tpu.controllers.autoscale import (
    CronJobController,
    DisruptionController,
    HorizontalPodAutoscalerController,
)
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.controllers.certificates import (
    CSRApprovingController,
    CSRSigningController,
)
from kubernetes_tpu.controllers.cloudctrl import (
    AttachDetachController,
    PersistentVolumeBinder,
    RouteController,
    ServiceLBController,
)
from kubernetes_tpu.controllers.daemonset import DaemonSetController
from kubernetes_tpu.controllers.deployment import DeploymentController
from kubernetes_tpu.controllers.endpoint import EndpointController
from kubernetes_tpu.controllers.gc import GarbageCollector, PodGCController
from kubernetes_tpu.controllers.job import JobController
from kubernetes_tpu.controllers.namespace import NamespaceController
from kubernetes_tpu.controllers.nodelifecycle import NodeLifecycleController
from kubernetes_tpu.controllers.quota_sa import (
    BootstrapSignerController,
    ResourceQuotaController,
    ServiceAccountController,
    TokenCleanerController,
    TTLController,
)
from kubernetes_tpu.controllers.replicaset import ReplicaSetController
from kubernetes_tpu.controllers.statefulset import StatefulSetController
from kubernetes_tpu.server.apiserver_lite import ApiServerLite


class ControllerManager:
    """The initializer map of controllermanager.go:313-339, one entry per
    reference controller (cloud-facing ones take the provider like
    --cloud-provider)."""

    def __init__(self, api: ApiServerLite, record_events: bool = True,
                 leader_elect: bool = False, identity: str = "cm-0",
                 cloud=None, token_issuer=None, ca=None):
        from kubernetes_tpu.auth.authn import CertAuthenticator
        from kubernetes_tpu.cloud import FakeCloud

        self.api = api
        self.factory = SharedInformerFactory(api)
        self.cloud = cloud if cloud is not None else FakeCloud()
        ca = ca if ca is not None else CertAuthenticator(b"cluster-ca-key")
        kw = dict(record_events=record_events)
        self.controllers: Dict[str, Controller] = {
            "replicaset": ReplicaSetController(api, self.factory, "ReplicaSet", **kw),
            "replicationcontroller": ReplicaSetController(
                api, self.factory, "ReplicationController", **kw),
            "deployment": DeploymentController(api, self.factory, **kw),
            "job": JobController(api, self.factory, **kw),
            "cronjob": CronJobController(api, self.factory, **kw),
            "daemonset": DaemonSetController(api, self.factory, **kw),
            "statefulset": StatefulSetController(api, self.factory, **kw),
            "endpoint": EndpointController(api, self.factory, **kw),
            "namespace": NamespaceController(api, self.factory),
            "garbagecollector": GarbageCollector(api, self.factory),
            "podgc": PodGCController(api, self.factory),
            "nodelifecycle": NodeLifecycleController(api, self.factory, **kw),
            "resourcequota": ResourceQuotaController(api, self.factory, **kw),
            "serviceaccount": ServiceAccountController(
                api, self.factory, token_issuer=token_issuer, **kw),
            "ttl": TTLController(api, self.factory, **kw),
            "bootstrapsigner": BootstrapSignerController(api, self.factory, **kw),
            "tokencleaner": TokenCleanerController(api, self.factory, **kw),
            "horizontalpodautoscaling": HorizontalPodAutoscalerController(
                api, self.factory, **kw),
            "disruption": DisruptionController(api, self.factory, **kw),
            "service": ServiceLBController(api, self.factory, self.cloud, **kw),
            "route": RouteController(api, self.factory, self.cloud, **kw),
            "persistentvolume-binder": PersistentVolumeBinder(
                api, self.factory, **kw),
            "attachdetach": AttachDetachController(api, self.factory,
                                                   cloud=self.cloud, **kw),
            "csrapproving": CSRApprovingController(api, self.factory, **kw),
            "csrsigning": CSRSigningController(api, self.factory, ca, **kw),
        }
        self.monitor_period = 5.0  # --node-monitor-period
        self.gc_resync_period = 60.0  # GC full-orphan-scan cadence
        self._ticker: Optional[threading.Thread] = None
        self._ticker_stop = threading.Event()
        self.elector: Optional[LeaderElector] = None
        if leader_elect:
            self.elector = LeaderElector(
                LeaseLock(api, "kube-controller-manager"), identity,
                on_started_leading=self._start_workers)
        self._running = False

    def register(self, name: str, controller: Controller) -> None:
        self.controllers[name] = controller
        if self._running:
            controller.run(workers=2)

    # ------------------------------------------------------- deterministic

    def pump_until_stable(self, max_rounds: int = 60) -> int:
        """Single-threaded convergence loop for tests/benchmarks: pump
        informers + every controller queue until a full round does nothing."""
        rounds = 0
        for _ in range(max_rounds):
            moved = self.factory.step_all()
            for c in self.controllers.values():
                moved += c.pump()
            rounds += 1
            if moved == 0:
                return rounds
        return rounds

    # ------------------------------------------------------------ threaded

    def start(self, workers: int = 2, poll: float = 0.02) -> None:
        self.factory.start(poll=poll)
        self.factory.wait_for_cache_sync()
        if self.elector is not None:
            self.elector.run()
        else:
            self._start_workers(workers)

    def _start_workers(self, workers: int = 2) -> None:
        if self._running:
            return  # leadership re-acquired: workers/ticker already live
        self._running = True
        for c in self.controllers.values():
            c.run(workers=workers)
        # periodic monitors: node heartbeat checks every --node-monitor-period
        # (5s); GC resyncs on their own much slower cadence (the reference
        # resyncs GC on the order of minutes, not the heartbeat period)
        def guarded(fn):
            # one bad tick must not kill monitoring forever
            # (Controller._worker gives workers the same shield)
            try:
                fn()
            except Exception:
                pass

        def tick_loop():
            last_gc = time.monotonic()
            while not self._ticker_stop.wait(self.monitor_period):
                guarded(self.controllers["nodelifecycle"].monitor_tick)
                guarded(self.controllers["cronjob"].tick)
                guarded(self.controllers["horizontalpodautoscaling"].resync_all)
                if time.monotonic() - last_gc >= self.gc_resync_period:
                    last_gc = time.monotonic()
                    guarded(self.controllers["garbagecollector"].resync)
                    guarded(self.controllers["podgc"].resync)
                    guarded(self.controllers["resourcequota"].resync_all)

        t = threading.Thread(target=tick_loop, daemon=True, name="cm-ticker")
        t.start()
        self._ticker = t

    def stop(self) -> None:
        self._ticker_stop.set()
        if self._ticker is not None:
            self._ticker.join(timeout=2.0)
        if self.elector is not None:
            self.elector.stop()
        for c in self.controllers.values():
            c.stop()
        self.factory.stop()
