"""Node lifecycle controller: heartbeat monitoring + zone-aware eviction.

Mirrors pkg/controller/node/node_controller.go:

- monitorNodeStatus (:523): a node whose heartbeat is older than
  node_monitor_grace_period gets its Ready condition forced to Unknown (the
  controller, not the dead kubelet, writes this).
- pod eviction (:399): nodes NotReady/Unknown longer than
  pod_eviction_timeout have their pods deleted — via per-zone token-bucket
  rate limiters (scheduler/rate_limited_queue.go), default
  --node-eviction-rate=0.1/s.
- zone disruption dampening (:701): per-zone health states — Normal /
  PartialDisruption (>= unhealthy_threshold unhealthy -> reduced
  secondary rate) / FullDisruption (ALL unhealthy -> evictions STOP; the
  partition is assumed to be on the master's side).
- TaintBasedEvictions (kube_features.go:83, off by default): instead of
  deleting pods, taint the node NoExecute `unreachable`/`not-ready`; the
  NoExecuteTaintManager (scheduler/taint_controller.go) then deletes pods
  lacking a matching toleration.

Tick-driven (monitor_tick), clock-injectable; ControllerManager registers a
periodic thread in threaded mode.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List

from kubernetes_tpu.api.types import (
    ConditionStatus,
    Node,
    NodeCondition,
    Taint,
    TaintEffect,
)
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.server.apiserver_lite import ApiServerLite, Conflict, NotFound
from kubernetes_tpu.utils import features

ZONE_LABEL = "failure-domain.beta.kubernetes.io/zone"
TAINT_UNREACHABLE = "node.alpha.kubernetes.io/unreachable"
TAINT_NOT_READY = "node.alpha.kubernetes.io/notReady"

# defaults from cmd/kube-controller-manager/app/options (v1.7)
DEFAULT_GRACE_PERIOD = 40.0          # --node-monitor-grace-period
DEFAULT_EVICTION_TIMEOUT = 300.0     # --pod-eviction-timeout
DEFAULT_EVICTION_RATE = 0.1          # --node-eviction-rate
DEFAULT_SECONDARY_RATE = 0.01        # --secondary-node-eviction-rate
DEFAULT_UNHEALTHY_THRESHOLD = 0.55   # --unhealthy-zone-threshold
DEFAULT_LARGE_CLUSTER_SIZE = 50      # --large-cluster-size-threshold


class _TokenBucket:
    """RateLimitedTimedQueue's flowcontrol bucket, reduced: capacity 1 burst
    in spirit of the default qps=0.1."""

    def __init__(self, rate: float, now: Callable[[], float]):
        self.rate = rate
        self._now = now
        self._tokens = 1.0
        self._last = now()

    def set_rate(self, rate: float) -> None:
        self.rate = rate

    def try_take(self) -> bool:
        now = self._now()
        self._tokens = min(1.0, self._tokens + (now - self._last) * self.rate)
        self._last = now
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return True
        return False


class NodeLifecycleController(Controller):
    name = "node-lifecycle-controller"

    def __init__(self, api: ApiServerLite, factory: SharedInformerFactory,
                 grace_period: float = DEFAULT_GRACE_PERIOD,
                 eviction_timeout: float = DEFAULT_EVICTION_TIMEOUT,
                 eviction_rate: float = DEFAULT_EVICTION_RATE,
                 secondary_rate: float = DEFAULT_SECONDARY_RATE,
                 unhealthy_threshold: float = DEFAULT_UNHEALTHY_THRESHOLD,
                 large_cluster_size: int = DEFAULT_LARGE_CLUSTER_SIZE,
                 record_events: bool = True,
                 now: Callable[[], float] = time.monotonic):
        super().__init__(api, record_events=record_events)
        self._now = now
        self.grace_period = grace_period
        self.eviction_timeout = eviction_timeout
        self.eviction_rate = eviction_rate
        self.secondary_rate = secondary_rate
        self.unhealthy_threshold = unhealthy_threshold
        self.large_cluster_size = large_cluster_size
        self.node_informer = factory.informer("Node")
        self.pod_informer = factory.informer("Pod")
        self.pod_informer.store.add_index(
            "node", lambda p: [p.node_name] if p.node_name else [])
        self._zone_buckets: Dict[str, _TokenBucket] = {}
        # node -> time we first observed it (probeTimestamp in the reference's
        # nodeStatusMap): a node that has never heartbeat — static/decoded
        # Node objects have heartbeat=0.0 — gets grace from first observation,
        # not from the epoch
        self._first_seen: Dict[str, float] = {}
        # node -> monotonic time it was first seen unhealthy
        self._unhealthy_since: Dict[str, float] = {}
        # nodes already drained — out of the eviction queue until they
        # recover (the RateLimitedTimedQueue Remove-on-process behavior)
        self._evicted: set = set()
        self.zone_states: Dict[str, str] = {}

    # --------------------------------------------------------------- monitor

    def monitor_tick(self) -> None:
        """One monitorNodeStatus pass over all nodes."""
        now = self._now()
        nodes: List[Node] = self.node_informer.store.list()
        by_zone: Dict[str, List[Node]] = {}
        for node in nodes:
            by_zone.setdefault(node.labels.get(ZONE_LABEL, ""), []).append(node)

        for zone, zone_nodes in by_zone.items():
            unhealthy = [n for n in zone_nodes if not self._healthy(n, now)]
            state = self._zone_state(len(zone_nodes), len(unhealthy))
            self.zone_states[zone] = state
            bucket = self._zone_buckets.get(zone)
            if bucket is None:
                bucket = _TokenBucket(self.eviction_rate, self._now)
                self._zone_buckets[zone] = bucket
            if state == "PartialDisruption":
                # large zones throttle; small zones stop entirely
                # (node_controller.go:701 ReducedQPSFunc)
                bucket.set_rate(self.secondary_rate
                                if len(zone_nodes) > self.large_cluster_size
                                else 0.0)
            else:
                bucket.set_rate(self.eviction_rate)

            for node in zone_nodes:
                if self._healthy(node, now):
                    self._unhealthy_since.pop(node.name, None)
                    self._evicted.discard(node.name)
                    self._mark_healthy(node)
                    continue
                since = self._unhealthy_since.setdefault(node.name, now)
                self._mark_unknown(node)
                if state == "FullDisruption":
                    continue  # assume master-side partition; don't evict
                if now - since >= self.eviction_timeout:
                    if features.enabled("TaintBasedEvictions"):
                        self._apply_noexecute_taint(node)
                        self._evict_intolerant_pods(node, bucket)
                    elif node.name not in self._evicted and bucket.try_take():
                        self._evicted.add(node.name)
                        self._evict_pods(node)

    def _healthy(self, node: Node, now: float) -> bool:
        last = max(node.heartbeat, self._first_seen.setdefault(node.name, now))
        return now - last < self.grace_period

    def _zone_state(self, total: int, unhealthy: int) -> str:
        if total == 0:
            return "Normal"
        if unhealthy == total:
            return "FullDisruption"
        if unhealthy / total >= self.unhealthy_threshold:
            return "PartialDisruption"
        return "Normal"

    # -------------------------------------------------------------- actions

    def _mark_unknown(self, node: Node) -> None:
        """Force Ready=Unknown: the kubelet stopped reporting
        (node_controller.go tryUpdateNodeStatus)."""
        if node.condition("Ready") == ConditionStatus.UNKNOWN:
            return
        conds = [c for c in node.conditions if c.type != "Ready"]
        conds.append(NodeCondition("Ready", ConditionStatus.UNKNOWN))
        try:
            fresh = self.api.get("Node", "", node.name)
            self.api.update("Node", dataclasses.replace(fresh, conditions=conds),
                            expect_rv=fresh.resource_version)
            self.event("Node", node.name, "Normal", "NodeNotReady",
                       f"Node {node.name} status is now: Unknown")
        except (Conflict, NotFound):
            pass

    def _mark_healthy(self, node: Node) -> None:
        """Clear our NoExecute taints once the node reports again."""
        ours = {TAINT_UNREACHABLE, TAINT_NOT_READY}
        if not any(t.key in ours for t in node.taints):
            return
        try:
            fresh = self.api.get("Node", "", node.name)
            taints = [t for t in fresh.taints if t.key not in ours]
            self.api.update("Node", dataclasses.replace(fresh, taints=taints),
                            expect_rv=fresh.resource_version)
        except (Conflict, NotFound):
            pass

    def _apply_noexecute_taint(self, node: Node) -> None:
        if any(t.key == TAINT_UNREACHABLE for t in node.taints):
            return
        try:
            fresh = self.api.get("Node", "", node.name)
            taints = list(fresh.taints) + [
                Taint(TAINT_UNREACHABLE, effect=TaintEffect.NO_EXECUTE)]
            self.api.update("Node", dataclasses.replace(fresh, taints=taints),
                            expect_rv=fresh.resource_version)
        except (Conflict, NotFound):
            pass

    def _pods_on(self, node_name: str):
        return [p for p in self.pod_informer.store.by_index("node", node_name)
                if p.phase not in ("Succeeded", "Failed")]

    def _evict_pods(self, node: Node) -> None:
        """Delete-based eviction (whole node drained in one rate-limit token,
        matching deletePods in the reference)."""
        evicted = 0
        for p in self._pods_on(node.name):
            try:
                self.api.delete("Pod", p.namespace, p.name)
                evicted += 1
            except NotFound:
                pass
        if evicted:
            self.event("Node", node.name, "Normal", "DeletingAllPods",
                       f"Deleting {evicted} pods from unresponsive node")

    def _evict_intolerant_pods(self, node: Node, bucket: _TokenBucket) -> None:
        """NoExecuteTaintManager: pods without a matching NoExecute toleration
        are deleted (taint_controller.go processPodOnNode)."""
        noexec = [t for t in node.taints if t.effect == TaintEffect.NO_EXECUTE]
        noexec.append(Taint(TAINT_UNREACHABLE, effect=TaintEffect.NO_EXECUTE))
        for p in self._pods_on(node.name):
            tolerated = all(any(tol.tolerates(t) for tol in p.tolerations)
                            for t in noexec)
            if not tolerated and bucket.try_take():
                try:
                    self.api.delete("Pod", p.namespace, p.name)
                except NotFound:
                    pass

    # -------------------------------------------------------- queue plumbing

    def sync(self, key: str) -> None:
        self.monitor_tick()

    def resync(self) -> None:
        self.enqueue("monitor")
