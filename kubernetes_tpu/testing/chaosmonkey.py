"""Chaosmonkey: disruption registry + convergence assertion harness.

Mirror of the reference's fault-injection mechanism
(test/e2e/chaosmonkey/chaosmonkey.go): tests register interest in a
disruption; the harness runs every test's Setup, fires the disruption
mid-flight, then runs every Test and Teardown. Here the "cluster" is the
in-process rig (apiserver-lite + hollow fleet + controllers + scheduler),
so disruptions are first-class functions over live components — kill the
scheduler, crash a kubelet, partition the watch stream, restart the
apiserver from its WAL — and the invariant checked after every storm is
the reference's level-triggered promise: the system re-converges to
all-pods-bound with no double binds (SURVEY §5.3/§5.4).
"""

from __future__ import annotations

from typing import Callable, List, Optional


class Test:
    """chaosmonkey.Test: Setup runs before the disruption, Test during/
    after it, Teardown last (chaosmonkey.go:33-60)."""

    __test__ = False  # not a pytest collection target

    def __init__(self, setup: Optional[Callable[[], None]] = None,
                 test: Optional[Callable[[], None]] = None,
                 teardown: Optional[Callable[[], None]] = None,
                 name: str = ""):
        self.name = name
        self.setup = setup or (lambda: None)
        self.test = test or (lambda: None)
        self.teardown = teardown or (lambda: None)


class Chaosmonkey:
    def __init__(self, disruption: Callable[[], None]):
        self.disruption = disruption
        self.tests: List[Test] = []

    def register(self, test: Test) -> None:
        self.tests.append(test)

    def register_interface(self, setup=None, test=None, teardown=None,
                           name: str = "") -> None:
        self.register(Test(setup, test, teardown, name))

    def do(self) -> None:
        """Setup all -> disrupt -> Test all -> Teardown all
        (chaosmonkey.go:78-106; sequential rather than goroutine-per-test —
        the rig is single-process)."""
        done: List[Test] = []
        try:
            for t in self.tests:
                t.setup()
                done.append(t)
            self.disruption()
            for t in done:
                t.test()
        finally:
            for t in reversed(done):
                t.teardown()
