"""Seeded churn / fault-injection harness for the always-on engine (ISSUE 8).

The r10 headline (20k pods/s sustained, p99 create->bound ~100 ms) was
measured on a QUIET cluster. The reference system's whole design is
level-triggered reconciliation under exactly the conditions that number
never saw (SURVEY §5.3/§5.4): nodes die and flap mid-storm, pods are
evicted, labels mutate under rolling updates, and the bind API fails or
times out. This module makes those conditions a deterministic, seeded,
replayable input so the streaming loop's robustness claims are MEASURED:

- ``FaultyBindApi`` wraps an ApiServerLite and injects bind faults at
  seeded per-binding rates. Two fault shapes, because they heal
  differently: a FAILURE returns an error and the write never lands
  (the scheduler forgets + requeues — the clean retry); a TIMEOUT
  returns an error but the write DID land — the at-most-once ambiguity
  every RPC client lives with. The scheduler forgets + requeues, the
  retry's bind is refused by the store ("already assigned"), and the
  watch confirmation heals the cache — exactly-once holds at the store,
  which is the invariant tests/test_chaos.py audits end to end.

- ``make_churn_schedule`` compiles a ChurnConfig into a frozen,
  seed-deterministic list of timed operations (node kills + respawns,
  NotReady flaps, cordon/uncordon, zone relabels, evictions, rolling
  updates). The SAME schedule object can drive a wall-clock thread
  (bench.py's churn scenario) or be applied at step boundaries (the
  frozen churn-trace A/B in tests) — determinism is the point: a churn
  bug reproduces from (seed, config), not from a lucky race.

- ``ChurnInjector`` applies a schedule against a live apiserver and
  counts what it did, so the bench JSON reports the offered fault load
  next to the sustained throughput it was absorbed under.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from kubernetes_tpu.api.types import ConditionStatus, Node, NodeCondition
from kubernetes_tpu.server.apiserver_lite import ApiServerLite, NotFound

ZONES = ["zone-a", "zone-b", "zone-c"]


# ---------------------------------------------------------------- bind faults


class FaultyBindApi:
    """ApiServerLite proxy injecting seeded bind faults on the BULK paths
    (the only bind paths the scheduler uses — engine/scheduler._bind_bulk
    prefers bind_pods_bulk and falls back to bind_many; both are wrapped,
    so injected faults exercise the backoff requeue on the streaming AND
    classic rounds). Reads delegate untouched.

    fail_rate:    probability a binding errors WITHOUT landing.
    timeout_rate: probability a binding errors but DID land (the
                  at-most-once ambiguity: the caller cannot distinguish a
                  lost request from a lost response).

    The VICTIM-DELETE seam (ISSUE 14): ``preempt_pods_bulk`` — the
    store's atomic evict+bind — gets the same two fault shapes, drawn
    PER VICTIM: any victim drawing a FAILURE aborts the whole commit
    with nothing landed (the store op is all-or-nothing, so a per-victim
    wire fault manifests as the batch erroring before application); any
    drawing a TIMEOUT lets the whole commit land and then loses the
    response — the scheduler must treat it as rolled back while the
    watch stream heals the divergence. Both shapes preserve zero
    partial preemptions by construction.

    evict_fail_rate:    per-victim probability the preempt commit errors
                        WITHOUT landing.
    evict_timeout_rate: per-victim probability the preempt commit LANDS
                        (evictions AND the bind) but errors anyway.
    """

    def __init__(self, api: ApiServerLite, fail_rate: float = 0.0,
                 timeout_rate: float = 0.0, seed: int = 0,
                 evict_fail_rate: float = 0.0,
                 evict_timeout_rate: float = 0.0):
        self._api = api
        self._rng = random.Random(seed)
        self.fail_rate = fail_rate
        self.timeout_rate = timeout_rate
        self.evict_fail_rate = evict_fail_rate
        self.evict_timeout_rate = evict_timeout_rate
        self.injected_failures = 0
        self.injected_timeouts = 0
        self.injected_evict_failures = 0
        self.injected_evict_timeouts = 0

    def __getattr__(self, name):
        return getattr(self._api, name)

    def _bind_with_faults(self, items, inner_bind) -> List[Optional[str]]:
        """Shared fault body: draw per-binding faults, delegate everything
        except pure failures to ``inner_bind`` as ONE batch (timeouts
        included — the write LANDS, only the response is lost), then
        stitch results back in order, injected errors winning."""
        out: List[Optional[str]] = [None] * len(items)
        apply_idx: List[int] = []
        for i in range(len(items)):
            r = self._rng.random()
            if r < self.fail_rate:
                out[i] = "injected: bind unavailable"
                self.injected_failures += 1
            elif r < self.fail_rate + self.timeout_rate:
                out[i] = "injected: bind timeout"
                self.injected_timeouts += 1
                apply_idx.append(i)
            else:
                apply_idx.append(i)
        if apply_idx:
            real = inner_bind([items[i] for i in apply_idx])
            for i, err in zip(apply_idx, real):
                if out[i] is None:  # keep the injected-timeout error
                    out[i] = err
        return out

    def bind_pods_bulk(self, pods) -> List[Optional[str]]:
        return self._bind_with_faults(pods, self._api.bind_pods_bulk)

    def bind_many(self, bindings) -> List[Optional[str]]:
        return self._bind_with_faults(bindings, self._api.bind_many)

    def preempt_pods_bulk(self, victims, binding) -> Optional[str]:
        """Atomic evict+bind with per-victim fault draws (class
        docstring): FAILURE wins over TIMEOUT, either yields ONE error
        for the whole commit — failure before the store op (nothing
        lands), timeout after it (everything lands, response lost)."""
        fail = timeout = False
        for _ in range(max(len(victims), 1)):
            r = self._rng.random()
            if r < self.evict_fail_rate:
                fail = True
            elif r < self.evict_fail_rate + self.evict_timeout_rate:
                timeout = True
        if fail:
            self.injected_evict_failures += 1
            return "injected: evict unavailable"
        err = self._api.preempt_pods_bulk(victims, binding)
        if err is None and timeout:
            self.injected_evict_timeouts += 1
            return "injected: evict timeout (landed)"
        return err


def extender_store_binder(api):
    """Adapt an ApiServerLite (or a FaultyBindApi proxy around one) into
    the extender backend's ``binder`` callable (ISSUE 9): the multi-
    frontend bench/tests bind through the REAL store so exactly-once is
    audited against store truth, with FaultyBindApi injecting the same
    failure/timeout shapes the streaming loop is hardened against.

    Store-level idempotence: a bind refused with "already assigned to
    node <same node>" heals to SUCCESS — that is precisely the landed-
    timeout replay (the write survived, only the response was lost), and
    treating it as an error would make the BindLedger's convergent replay
    impossible. "already assigned" to a DIFFERENT node stays an error
    (the caller is trying to double-book; the store's refusal IS the
    exactly-once guarantee)."""
    from kubernetes_tpu.api.types import Pod

    def _bind(pod_name: str, pod_namespace: str, pod_uid: str,
              node: str) -> None:
        stub = Pod(name=pod_name, namespace=pod_namespace, uid=pod_uid)
        stub.node_name = node
        err = api.bind_pods_bulk([stub])[0]
        if err and f"already assigned to node {node}" in err:
            return  # landed-timeout replay: idempotent success
        if err:
            raise RuntimeError(err)

    return _bind


# ------------------------------------------------------------------ schedule


@dataclass(frozen=True)
class ChurnOp:
    t: float          # due instant, seconds from schedule start
    kind: str         # kill | respawn | flap_down | flap_up | cordon |
    #                   uncordon | relabel | evict
    node: str = ""
    zone: str = ""    # relabel target
    evict_slot: int = 0  # seeded pick among currently-bound pods


@dataclass
class ChurnConfig:
    """Production-shaped fault rates (all per minute, fractions of the
    node count where applicable). Defaults follow the ROADMAP acceptance
    shape: sustained 10%/min node churn plus flaps/evictions/relabels."""

    seed: int = 0
    node_churn_per_min: float = 0.10   # fraction of nodes killed/min
    respawn_s: float = 2.0             # dead node returns after this
    flap_per_min: float = 0.05         # fraction of nodes NotReady-flapped
    flap_down_s: float = 1.0
    cordon_per_min: float = 0.02
    cordon_s: float = 1.5
    relabel_per_min: float = 0.05      # zone-label mutations (rolling-
    #                                    update-shaped topology drift)
    evict_per_min_abs: float = 60.0    # absolute evictions per minute
    bind_fail_rate: float = 0.0
    bind_timeout_rate: float = 0.0


def make_churn_schedule(node_names: List[str], cfg: ChurnConfig,
                        duration_s: float) -> List[ChurnOp]:
    """Compile a config into a frozen op list, sorted by due time.
    Deterministic in (node_names, cfg, duration_s) — the replayable churn
    trace both the bench thread and the A/B tests consume. Kill targets
    are drawn without replacement per overlapping window so a node is
    never killed while already dead."""
    rng = random.Random(cfg.seed)
    ops: List[ChurnOp] = []
    n = len(node_names)
    minutes = duration_s / 60.0

    def uniform_times(count: float) -> List[float]:
        c = int(count)
        if rng.random() < count - c:
            c += 1
        return sorted(rng.uniform(0.0, duration_s) for _ in range(c))

    # node kills + respawns: draw targets without replacement among nodes
    # not currently dead at the kill instant
    dead_until: Dict[str, float] = {}
    for t in uniform_times(cfg.node_churn_per_min * n * minutes):
        alive = [nm for nm in node_names if dead_until.get(nm, -1.0) < t]
        if not alive:
            continue
        nm = alive[rng.randrange(len(alive))]
        dead_until[nm] = t + cfg.respawn_s
        ops.append(ChurnOp(t, "kill", node=nm))
        ops.append(ChurnOp(t + cfg.respawn_s, "respawn", node=nm))
    for t in uniform_times(cfg.flap_per_min * n * minutes):
        nm = node_names[rng.randrange(n)]
        ops.append(ChurnOp(t, "flap_down", node=nm))
        ops.append(ChurnOp(t + cfg.flap_down_s, "flap_up", node=nm))
    for t in uniform_times(cfg.cordon_per_min * n * minutes):
        nm = node_names[rng.randrange(n)]
        ops.append(ChurnOp(t, "cordon", node=nm))
        ops.append(ChurnOp(t + cfg.cordon_s, "uncordon", node=nm))
    for t in uniform_times(cfg.relabel_per_min * n * minutes):
        nm = node_names[rng.randrange(n)]
        ops.append(ChurnOp(t, "relabel", node=nm,
                           zone=ZONES[rng.randrange(len(ZONES))]))
    for t in uniform_times(cfg.evict_per_min_abs * minutes):
        ops.append(ChurnOp(t, "evict", evict_slot=rng.randrange(1 << 30)))
    ops.sort(key=lambda op: (op.t, op.kind, op.node))
    return ops


# ------------------------------------------------------------------ injector


class ChurnInjector:
    """Applies a frozen schedule against a live apiserver. Call
    ``apply_until(t)`` from the owner's clock (a wall-clock thread in the
    bench, a step counter in tests) — ops are consumed in order, each
    applied exactly once. Idempotent against the cluster's own drift: a
    kill of an already-gone node or an eviction with nothing bound is
    counted as a no-op, not an error."""

    def __init__(self, api: ApiServerLite, schedule: List[ChurnOp]):
        self.api = api
        self.schedule = schedule
        self._next = 0
        self._spec: Dict[str, Node] = {}  # last-seen spec for respawn
        self.applied: Dict[str, int] = {}
        self.noop = 0

    def done(self) -> bool:
        return self._next >= len(self.schedule)

    def apply_until(self, t: float) -> int:
        applied = 0
        while self._next < len(self.schedule) \
                and self.schedule[self._next].t <= t:
            self._apply(self.schedule[self._next])
            self._next += 1
            applied += 1
        return applied

    def _get_node(self, name: str) -> Optional[Node]:
        try:
            return self.api.get("Node", "", name)
        except NotFound:
            return None

    def _count(self, op: ChurnOp) -> None:
        self.applied[op.kind] = self.applied.get(op.kind, 0) + 1
        from kubernetes_tpu.observability.recorder import (
            CHURN_OP,
            CHURN_OP_CODES,
            RECORDER,
        )
        if RECORDER.enabled:
            # flight-recorder marker (ISSUE 13): the fault lands on the
            # same time axis as the waves it perturbed
            RECORDER.record(CHURN_OP, a=CHURN_OP_CODES.get(op.kind, -1),
                            b=1)

    def _apply(self, op: ChurnOp) -> None:
        api = self.api
        if op.kind == "kill":
            node = self._get_node(op.node)
            if node is None:
                self.noop += 1
                return
            self._spec[op.node] = node
            try:
                api.delete("Node", "", op.node)
            except NotFound:
                self.noop += 1
                return
        elif op.kind == "respawn":
            spec = self._spec.get(op.node)
            if spec is None or self._get_node(op.node) is not None:
                self.noop += 1
                return
            api.create("Node", dataclasses.replace(
                spec, labels=dict(spec.labels),
                conditions=[dataclasses.replace(c) for c in spec.conditions],
                resource_version=0))
        elif op.kind in ("flap_down", "flap_up", "cordon", "uncordon",
                         "relabel"):
            node = self._get_node(op.node)
            if node is None:
                self.noop += 1
                return
            conditions = [dataclasses.replace(c) for c in node.conditions]
            if op.kind in ("flap_down", "flap_up"):
                status = ConditionStatus.FALSE if op.kind == "flap_down" \
                    else ConditionStatus.TRUE
                for c in conditions:
                    if c.type == "Ready":
                        c.status = status
                        break
                else:
                    conditions.append(NodeCondition("Ready", status))
            labels = dict(node.labels)
            if op.kind == "relabel":
                labels["failure-domain.beta.kubernetes.io/zone"] = op.zone
            api.update("Node", dataclasses.replace(
                node, labels=labels, conditions=conditions,
                unschedulable=(op.kind == "cordon"
                               if op.kind in ("cordon", "uncordon")
                               else node.unschedulable)))
        elif op.kind == "evict":
            bound = [p for p in api.list("Pod")[0] if p.node_name]
            if not bound:
                self.noop += 1
                return
            victim = bound[op.evict_slot % len(bound)]
            try:
                api.delete("Pod", victim.namespace, victim.name)
            except NotFound:
                self.noop += 1
                return
        self._count(op)

    # ------------------------------------------------------------- thread

    def run_thread(self, stop: threading.Event,
                   t0: Optional[float] = None) -> threading.Thread:
        """Wall-clock driver for the bench: applies ops as they come due
        until the schedule is exhausted or ``stop`` is set."""
        start = time.monotonic() if t0 is None else t0

        def _run():
            while not self.done() and not stop.is_set():
                now = time.monotonic() - start
                self.apply_until(now)
                if self._next < len(self.schedule):
                    delay = self.schedule[self._next].t - (
                        time.monotonic() - start)
                    if delay > 0:
                        stop.wait(min(delay, 0.05))

        th = threading.Thread(target=_run, daemon=True)
        th.start()
        return th


# ------------------------------------------------------- rolling updates


def diurnal_rate(base: float, amp: float = 0.5, period_s: float = 60.0):
    """Offered-rate curve shaped like a day: rate(t) = base * (1 + amp *
    sin(2*pi*t/period)). The rolling-update scenario rides its replacement
    waves on TOP of this curve, so the update is measured against a
    cluster whose background load is moving — the deploy-shaped traffic
    of ISSUE 18, not a quiet box."""
    import math

    def rate(t: float) -> float:
        return max(0.0, base * (1.0 + amp *
                                math.sin(2.0 * math.pi * t / period_s)))

    return rate


@dataclass
class RollingUpdateConfig:
    """Deployment-shaped rolling update (the reference's deployment
    controller semantics, driven against store truth): `replicas` old-
    revision pods are replaced by new-revision pods under the two
    standard bounds — at most `max_surge` pods OVER the replica count
    may exist at once, and availability may fall at most
    `max_unavailable` UNDER it (a replacement counts available once it
    is bound)."""

    replicas: int = 200
    max_surge: int = 25
    max_unavailable: int = 25
    app: str = "web"
    old_rev: str = "1"
    new_rev: str = "2"


class RollingUpdateDriver:
    """Evict-and-recreate controller: each ``step()`` observes STORE
    truth (never its own bookkeeping — a controller trusting its own
    view would hide scheduler lag), creates replacements up to the surge
    bound, and evicts old-revision pods down to the unavailability
    bound. The driver records the observed extremes so the bench can
    report `surge_respected` / `unavailable_respected` as measured
    facts rather than configuration echoes.

    ``make_replacement(i)`` must return a pod labeled
    {app: cfg.app, rev: cfg.new_rev}; the driver stamps each creation
    in ``create_ts`` (key -> monotonic instant) for the caller's
    create->bound join."""

    def __init__(self, api: ApiServerLite, cfg: RollingUpdateConfig,
                 make_replacement):
        self.api = api
        self.cfg = cfg
        self.make_replacement = make_replacement
        self.create_ts: Dict[str, float] = {}
        self.replacement_keys: List[str] = []
        self._created = 0
        self.evicted = 0
        self.noop = 0
        self.started_at: Optional[float] = None
        self.completed_at: Optional[float] = None
        self.max_total_seen = 0
        self.min_available_seen = cfg.replicas

    def _observe(self):
        cfg = self.cfg
        pods = [p for p in self.api.list("Pod")[0]
                if p.labels.get("app") == cfg.app]
        old = [p for p in pods if p.labels.get("rev") == cfg.old_rev]
        new = [p for p in pods if p.labels.get("rev") == cfg.new_rev]
        return old, new

    def step(self) -> bool:
        """One controller pass; returns True once the update is complete
        (no old-revision pod remains and every replacement is bound)."""
        cfg = self.cfg
        now = time.monotonic()
        if self.started_at is None:
            self.started_at = now
        old, new = self._observe()
        new_bound = sum(1 for p in new if p.node_name)
        available = sum(1 for p in old if p.node_name) + new_bound
        total = len(old) + len(new)
        self.max_total_seen = max(self.max_total_seen, total)
        self.min_available_seen = min(self.min_available_seen, available)
        # surge-bounded creation: never exceed replicas + max_surge pods
        # of this app in the store, never create more than replicas
        # replacements overall
        n_create = min(cfg.replicas + cfg.max_surge - total,
                       cfg.replicas - self._created)
        for _ in range(max(n_create, 0)):
            p = self.make_replacement(self._created)
            self.api.create("Pod", p)
            self.create_ts[p.key()] = time.monotonic()
            self.replacement_keys.append(p.key())
            self._created += 1
        # unavailability-bounded eviction: only as many old pods as keeps
        # available >= replicas - max_unavailable (replacements created
        # above are NOT yet available — they count only once bound)
        n_evict = available - (cfg.replicas - cfg.max_unavailable)
        victims = sorted((p for p in old if p.node_name),
                         key=lambda p: p.name)
        for p in victims[:max(n_evict, 0)]:
            try:
                self.api.delete("Pod", p.namespace, p.name)
            except NotFound:
                self.noop += 1
            else:
                self.evicted += 1
        # completion is judged on THIS step's pre-action observation: the
        # step after the last eviction sees an empty old set and every
        # replacement bound
        done = not old and self._created >= cfg.replicas \
            and new_bound >= cfg.replicas
        if done and self.completed_at is None:
            self.completed_at = time.monotonic()
        return done

    def bounds_report(self) -> Dict[str, object]:
        cfg = self.cfg
        return {
            "replicas": cfg.replicas,
            "max_surge": cfg.max_surge,
            "max_unavailable": cfg.max_unavailable,
            "max_total_seen": int(self.max_total_seen),
            "min_available_seen": int(self.min_available_seen),
            "surge_respected":
                bool(self.max_total_seen <= cfg.replicas + cfg.max_surge),
            "unavailable_respected":
                bool(self.min_available_seen
                     >= cfg.replicas - cfg.max_unavailable),
            "evicted": int(self.evicted),
            "created": int(self._created),
        }

    def run_thread(self, stop: threading.Event,
                   poll_s: float = 0.01) -> threading.Thread:
        """Wall-clock driver for the bench: steps the controller until
        the update completes or ``stop`` is set."""

        def _run():
            while not stop.is_set():
                if self.step():
                    break
                stop.wait(poll_s)

        th = threading.Thread(target=_run, daemon=True)
        th.start()
        return th


# ----------------------------------------------------- store-truth audits


def audit_store_transitions(api) -> Dict[str, Dict[str, int]]:
    """Walk the store's retained event log and count per-pod BINDS
    (unbound -> bound transitions, preloaded-bound ADDs included) and
    EVICTIONS (bound -> unbound). The log orders transitions, so 'one
    bound node per preemptor ever' and 'every victim evicted at most
    once' are direct assertions over these counts — the exactly-once
    audit extended to the victim seam (ISSUE 14). Callers must size the
    store's max_log to retain the whole scenario."""
    binds: Dict[str, int] = {}
    evicts: Dict[str, int] = {}
    state: Dict[str, str] = {}
    for ev in list(getattr(api, "_log")):
        if ev.kind != "Pod":
            continue
        key = ev.obj.key()
        node = ev.obj.node_name or ""
        if ev.type == "DELETED":
            state.pop(key, None)
            continue
        prev = state.get(key, "")
        if node and not prev:
            binds[key] = binds.get(key, 0) + 1
        elif prev and not node:
            evicts[key] = evicts.get(key, 0) + 1
        state[key] = node
    return {"binds": binds, "evicts": evicts}


def audit_cache_vs_store(sched, api) -> List[str]:
    """Ghost-capacity audit (ISSUE 14): after quiesce, every pod the
    scheduler cache counts against a node must be bound there at the
    store, and vice versa — an evicted victim still resident in a
    NodeInfo would be phantom occupancy 'freeing' capacity that is not
    free. Assumed (in-flight optimistic) claims are exempt. Returns the
    discrepancy list (empty = clean)."""
    store_bound = {p.key(): p.node_name
                   for p in api.list("Pod")[0] if p.node_name}
    with sched.cache._lock:
        assumed = {k for k, st in sched.cache._pod_states.items()
                   if st.assumed}
        cache_bound = {p.key(): name
                       for name, info in sched.cache._nodes.items()
                       for p in info.pods}
    problems: List[str] = []
    for k, n in cache_bound.items():
        if k in assumed:
            continue
        if store_bound.get(k) != n:
            problems.append(
                f"cache counts {k} on {n}; store says "
                f"{store_bound.get(k, '<unbound>')}")
    for k in store_bound:
        if k not in cache_bound:
            problems.append(f"store-bound {k} missing from cache")
    return problems


# -------------------------------------------------------- cell brownout


@dataclass(frozen=True)
class CellBrownoutOp:
    """One cell-level fault for the federation tier (ISSUE 20): the cell
    goes NotReady at ``t`` (router evacuates its pending pods through
    the spillover path) and recovers at ``t + down_s``."""

    t: float
    cell: str
    down_s: float


def make_brownout_schedule(cell_names: List[str], duration_s: float,
                           down_s: float = 2.0, count: int = 1,
                           seed: int = 0) -> List[CellBrownoutOp]:
    """Frozen brownout schedule, deterministic in its arguments (the
    same replayable-trace contract as make_churn_schedule). Instants
    land in the middle 80% of the window — a brownout at the very edge
    would measure shutdown, not spillover — and never overlap on the
    same cell."""
    rng = random.Random(seed ^ 0xB10)
    ops: List[CellBrownoutOp] = []
    busy_until: Dict[str, float] = {}
    lo, hi = 0.1 * duration_s, 0.9 * duration_s
    for _ in range(max(int(count), 0)):
        t = rng.uniform(lo, max(hi - down_s, lo))
        free = [c for c in cell_names if busy_until.get(c, -1.0) < t]
        if not free:
            continue
        cell = free[rng.randrange(len(free))]
        busy_until[cell] = t + down_s
        ops.append(CellBrownoutOp(t, cell, down_s))
    ops.sort(key=lambda op: (op.t, op.cell))
    return ops


class BrownoutDriver:
    """Applies a frozen brownout schedule against a FederationRouter.
    Call ``apply_until(t)`` from the owner's clock; each op's down and
    up phases fire exactly once. Returns evacuated-pod count applied in
    this call."""

    def __init__(self, router, schedule: List[CellBrownoutOp]):
        self._router = router
        self._downs = sorted(schedule, key=lambda op: op.t)
        self._ups = sorted(schedule, key=lambda op: op.t + op.down_s)
        self._di = 0
        self._ui = 0
        self.evacuated = 0

    def apply_until(self, t: float) -> int:
        moved = 0
        while self._di < len(self._downs) and self._downs[self._di].t <= t:
            op = self._downs[self._di]
            self._di += 1
            moved += self._router.brownout(op.cell)
        while self._ui < len(self._ups) \
                and self._ups[self._ui].t + self._ups[self._ui].down_s <= t:
            op = self._ups[self._ui]
            self._ui += 1
            self._router.recover(op.cell)
        self.evacuated += moved
        return moved

    def done(self) -> bool:
        return self._di >= len(self._downs) and self._ui >= len(self._ups)


__all__ = ["BrownoutDriver", "CellBrownoutOp", "ChurnConfig",
           "ChurnInjector", "ChurnOp", "FaultyBindApi",
           "RollingUpdateConfig", "RollingUpdateDriver",
           "audit_cache_vs_store", "audit_store_transitions",
           "diurnal_rate", "extender_store_binder",
           "make_brownout_schedule", "make_churn_schedule", "ZONES"]
