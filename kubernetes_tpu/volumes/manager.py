"""Kubelet-side volume manager: desired/actual state + reconciler.

Mirror of pkg/kubelet/volumemanager/:

- DesiredStateOfWorld (cache/desired_state_of_world.go): volumes the
  pods assigned to this node need mounted.
- ActualStateOfWorld (cache/actual_state_of_world.go): what is mounted.
- Reconciler (reconciler/reconciler.go): mount what's desired and not
  actual (waiting for attach on attachable plugins), unmount what's
  actual and no longer desired.
- WaitForAttachAndMount (volume_manager.go:339): what syncPod blocks on
  before containers start; a timeout surfaces as the FailedMount event.

The controller-attaches model is assumed (the v1.7 default on cloud
nodes): this manager never attaches — it observes the attach-detach
controller's record on the Node object (controllers/cloudctrl.py) and
reports `volumes_in_use` so the controller will not detach a mounted
volume (the node.status.volumesInUse contract,
volume_manager.go GetVolumesInUse).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from kubernetes_tpu.api.types import Pod, VolumeKind
from kubernetes_tpu.volumes.plugins import (
    VolumeError,
    VolumeHost,
    VolumePluginManager,
    VolumeSpec,
    resolve_spec,
)


@dataclass
class _MountRecord:
    spec: VolumeSpec
    plugin_name: str


class VolumeManager:
    def __init__(self, plugin_mgr: VolumePluginManager, host: VolumeHost):
        self.plugins = plugin_mgr
        self.host = host
        # desired: pod_key -> volume_name -> VolumeSpec
        self._desired: Dict[str, Dict[str, VolumeSpec]] = {}
        self._desired_pods: Dict[str, Pod] = {}
        # actual: pod_key -> volume_name -> record
        self._actual: Dict[str, Dict[str, _MountRecord]] = {}
        self._mount_errors: Dict[str, Dict[str, str]] = {}

    # ------------------------------------------------- desired state (DSW)

    def add_pod(self, pod: Pod) -> None:
        """desired_state_of_world_populator: register every pod volume;
        PVC dereference happens here so an unbound claim is a visible
        error, not a silent skip."""
        wants: Dict[str, VolumeSpec] = {}
        for v in pod.volumes:
            wants[v.name] = resolve_spec(v, self.host.api, pod.namespace)
        self._desired[pod.key()] = wants
        self._desired_pods[pod.key()] = pod

    def remove_pod(self, pod_key: str) -> None:
        self._desired.pop(pod_key, None)
        self._desired_pods.pop(pod_key, None)
        self._mount_errors.pop(pod_key, None)

    # ------------------------------------------------------- actual state

    def mounted_volumes(self, pod_key: str) -> Set[str]:
        return set(self._actual.get(pod_key, {}))

    def volumes_in_use(self) -> List[str]:
        """node.status.volumesInUse: attachable devices currently mounted
        by any pod on this node — the detach guard the attach-detach
        controller honors."""
        devs: Set[str] = set()
        for mounts in self._actual.values():
            for rec in mounts.values():
                src = rec.spec.source
                if self.plugins.find_plugin_by_name(
                        rec.plugin_name).attachable:
                    devs.add(f"{VolumeKind(src.kind).value}:{src.volume_id}")
        return sorted(devs)

    # --------------------------------------------------------- reconciler

    def reconcile(self) -> Tuple[int, int]:
        """One reconciler pass: (mounted, unmounted) this round. Mount
        failures are recorded per volume (read back by
        wait_for_attach_and_mount) and retried next pass — the
        reconciler never throws, like reconciler.go's
        operation-executor error swallowing."""
        mounted = unmounted = 0
        # unmount: actual but no longer desired
        for pod_key in list(self._actual):
            for vname in list(self._actual[pod_key]):
                if vname not in self._desired.get(pod_key, {}):
                    rec = self._actual[pod_key][vname]
                    plugin = self.plugins.find_plugin_by_name(
                        rec.plugin_name)
                    plugin.new_unmounter(
                        vname, pod_key, self.host).tear_down()
                    del self._actual[pod_key][vname]
                    unmounted += 1
            if not self._actual[pod_key]:
                del self._actual[pod_key]
                self.host.remove_pod_dir(pod_key)
        # mount: desired but not actual
        for pod_key, wants in self._desired.items():
            pod = self._desired_pods[pod_key]
            for vname, spec in wants.items():
                if vname in self._actual.get(pod_key, {}):
                    continue
                try:
                    plugin = self.plugins.find_plugin_by_spec(spec)
                    m = plugin.new_mounter(spec, pod, self.host)
                    m.set_up()
                except VolumeError as e:
                    self._mount_errors.setdefault(
                        pod_key, {})[vname] = str(e)
                    continue
                self._mount_errors.get(pod_key, {}).pop(vname, None)
                self._actual.setdefault(pod_key, {})[vname] = \
                    _MountRecord(spec, plugin.name)
                mounted += 1
        return mounted, unmounted

    # ------------------------------------------------ the syncPod contract

    def wait_for_attach_and_mount(self, pod: Pod, timeout: float = 2.0,
                                  poll: float = 0.01,
                                  now=time.monotonic,
                                  sleep=time.sleep) -> None:
        """volume_manager.go:339 WaitForAttachAndMount: block until every
        pod volume is mounted or raise with the unmounted set + last
        per-volume errors (kubelet turns this into FailedMount).

        timeout=0 is the non-blocking form: one reconcile pass, then
        report — what the hollow kubelet uses per sync pass so an
        unmountable volume never stalls the serialized pod workers on
        real wall-clock (the retry is the next sync, like the kubelet's
        periodic syncCh resync)."""
        self.add_pod(pod)
        want = set(self._desired[pod.key()])
        deadline = now() + timeout
        while True:
            self.reconcile()
            missing = want - self.mounted_volumes(pod.key())
            if not missing:
                return
            if now() >= deadline:
                errs = self._mount_errors.get(pod.key(), {})
                detail = "; ".join(
                    f"{v}: {errs.get(v, 'not yet attached/mounted')}"
                    for v in sorted(missing))
                raise VolumeError(
                    f"unmounted volumes={sorted(missing)}: {detail}")
            sleep(poll)

    def teardown_pod(self, pod_key: str) -> int:
        """Pod gone: drop desire and reconcile the unmounts."""
        self.remove_pod(pod_key)
        _, unmounted = self.reconcile()
        return unmounted
