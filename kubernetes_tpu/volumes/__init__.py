"""The volume subsystem's plugin layer: plugin interface + drivers +
the kubelet-side volume manager (reference: pkg/volume/, 42.8k LoC)."""

from kubernetes_tpu.volumes.plugins import (  # noqa: F401
    Attacher,
    Detacher,
    Mounter,
    Unmounter,
    VolumeHost,
    VolumePlugin,
    VolumePluginManager,
    VolumeSpec,
)
from kubernetes_tpu.volumes.drivers import default_plugins  # noqa: F401
from kubernetes_tpu.volumes.manager import VolumeManager  # noqa: F401
