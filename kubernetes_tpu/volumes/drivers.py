"""Concrete volume drivers — the in-framework mirror of the per-driver
dirs under pkg/volume/ (empty_dir/, host_path/, configmap/, secret/,
downwardapi/, projected/, local/, nfs/, gce_pd/, aws_ebs/, rbd/).

Selection mirrors FindPluginBySpec switching on the populated
VolumeSource member: scheduler-relevant kinds (GCE_PD/AWS_EBS/RBD/ISCSI/
SECRET/CONFIG_MAP) select by `Volume.kind`; the scheduling-inert kinds
that collapse to OTHER select by the `Volume.driver` source hint.

Semantics kept from the reference drivers:
- EmptyDir: fresh per-pod dir; medium "Memory" = tmpfs
  (pkg/volume/empty_dir/empty_dir.go mediumMemory).
- HostPath: binds the node filesystem — two pods on one node share it,
  pods on different nodes do not (pkg/volume/host_path/).
- ConfigMap/Secret: payload fetched from the API at SetUp; missing
  object = mount failure (pkg/volume/configmap/configmap.go SetUpAt);
  Secret values land base64-decoded (secret.go).
- DownwardAPI: pod fields rendered to files (downwardapi.go).
- Projected: configmap+secret+downwardAPI sources merged into one dir
  (pkg/volume/projected/).
- NFS: server:path export shared across nodes (pkg/volume/nfs/).
- Local: node-pinned PV (pkg/volume/local/) — mount fails on the wrong
  node, the error VolumeNode-predicate misconfigurations produce.
- GCE-PD / AWS-EBS / RBD: attachable block devices; mount requires the
  device attached first (pkg/volume/gce_pd/attacher.go WaitForAttach),
  content rides shared_fs keyed by device id so remount on another node
  sees the same bytes.
"""

from __future__ import annotations

import base64
from typing import List, Optional

from kubernetes_tpu.api.types import Pod, VolumeKind
from kubernetes_tpu.server.apiserver_lite import NotFound
from kubernetes_tpu.volumes.plugins import (
    Mounter,
    VolumeError,
    VolumeHost,
    VolumePlugin,
    VolumeSpec,
)


class _KindPlugin(VolumePlugin):
    """Selects on the scheduler-visible VolumeKind."""

    kind: VolumeKind = VolumeKind.OTHER

    def can_support(self, spec: VolumeSpec) -> bool:
        return VolumeKind(spec.source.kind) is self.kind


class _DriverPlugin(VolumePlugin):
    """Selects on the `driver` source hint for OTHER-kind volumes."""

    driver = ""

    def can_support(self, spec: VolumeSpec) -> bool:
        src = spec.source
        return VolumeKind(src.kind) is VolumeKind.OTHER \
            and src.driver == self.driver


# ------------------------------------------------------------ inert drivers


class EmptyDirMounter(Mounter):
    def set_up(self) -> None:
        self._target()  # fresh empty dict = the new empty dir


class EmptyDirPlugin(_DriverPlugin):
    name = "kubernetes.io/empty-dir"
    driver = "EmptyDir"

    def can_support(self, spec: VolumeSpec) -> bool:
        src = spec.source
        # EmptyDir is also the fallback for an OTHER volume with no
        # driver hint — the schedulers' tests build such pods freely
        return VolumeKind(src.kind) is VolumeKind.OTHER \
            and src.driver in ("EmptyDir", "")

    def new_mounter(self, spec, pod, host):
        return EmptyDirMounter(spec, pod, host)


class HostPathMounter(Mounter):
    def set_up(self) -> None:
        # bind mount: the pod dir aliases the node fs path
        path = self.spec.source.volume_id or "/"
        shared = self.host.node_fs.setdefault(path, {})
        self.host.pod_dir(self.pod.key())[self.spec.name] = shared


class HostPathPlugin(_DriverPlugin):
    name = "kubernetes.io/host-path"
    driver = "HostPath"

    def new_mounter(self, spec, pod, host):
        return HostPathMounter(spec, pod, host)


class ConfigMapMounter(Mounter):
    def set_up(self) -> None:
        if self.host.api is None:
            raise VolumeError("configmap volume needs an API host")
        try:
            cm = self.host.api.get("ConfigMap", self.pod.namespace,
                                   self.spec.source.volume_id)
        except NotFound:
            raise VolumeError(
                f'configmap "{self.spec.source.volume_id}" not found'
            ) from None
        tgt = self._target()
        tgt.clear()
        for k, v in cm.data.items():
            tgt[k] = v.encode() if isinstance(v, str) else v


class ConfigMapPlugin(_KindPlugin):
    name = "kubernetes.io/configmap"
    kind = VolumeKind.CONFIG_MAP

    def new_mounter(self, spec, pod, host):
        return ConfigMapMounter(spec, pod, host)


def _decode_secret_value(v) -> bytes:
    """Secret payloads are stored base64 (api/cluster.py Secret); files
    land decoded (pkg/volume/secret/secret.go). Non-base64 strings pass
    through encoded, bytes pass through untouched."""
    if not isinstance(v, str):
        return v
    try:
        return base64.b64decode(v, validate=True)
    except Exception:
        return v.encode()


class SecretMounter(Mounter):
    def set_up(self) -> None:
        if self.host.api is None:
            raise VolumeError("secret volume needs an API host")
        try:
            sec = self.host.api.get("Secret", self.pod.namespace,
                                    self.spec.source.volume_id)
        except NotFound:
            raise VolumeError(
                f'secret "{self.spec.source.volume_id}" not found'
            ) from None
        tgt = self._target()
        tgt.clear()
        for k, v in sec.data.items():
            tgt[k] = _decode_secret_value(v)


class SecretPlugin(_KindPlugin):
    name = "kubernetes.io/secret"
    kind = VolumeKind.SECRET

    def new_mounter(self, spec, pod, host):
        return SecretMounter(spec, pod, host)


def render_downward_api(pod: Pod) -> dict:
    """The downward-API field set v1.7 serves via fieldRef
    (pkg/fieldpath/fieldpath.go ExtractFieldPathAsString)."""
    return {
        "metadata.name": pod.name.encode(),
        "metadata.namespace": pod.namespace.encode(),
        "metadata.labels": "\n".join(
            f'{k}="{v}"' for k, v in sorted(pod.labels.items())).encode(),
        "metadata.annotations": "\n".join(
            f'{k}="{v}"' for k, v in
            sorted(pod.annotations.items())).encode(),
        "spec.nodeName": (pod.node_name or "").encode(),
    }


class DownwardAPIMounter(Mounter):
    def set_up(self) -> None:
        tgt = self._target()
        tgt.clear()
        tgt.update(render_downward_api(self.pod))


class DownwardAPIPlugin(_DriverPlugin):
    name = "kubernetes.io/downward-api"
    driver = "DownwardAPI"

    def new_mounter(self, spec, pod, host):
        return DownwardAPIMounter(spec, pod, host)


class ProjectedMounter(Mounter):
    """All-sources-in-one-dir (pkg/volume/projected/): volume_id is a
    comma-separated source list "configmap:name,secret:name,downwardAPI"."""

    def set_up(self) -> None:
        tgt = self._target()
        tgt.clear()
        for part in filter(None, self.spec.source.volume_id.split(",")):
            stype, _, sname = part.partition(":")
            if stype == "downwardAPI":
                tgt.update(render_downward_api(self.pod))
                continue
            kind = {"configmap": "ConfigMap", "secret": "Secret"}.get(stype)
            if kind is None:
                raise VolumeError(f"unknown projected source {stype!r}")
            if self.host.api is None:
                raise VolumeError("projected volume needs an API host")
            try:
                obj = self.host.api.get(kind, self.pod.namespace, sname)
            except NotFound:
                raise VolumeError(
                    f'projected source {kind} "{sname}" not found'
                ) from None
            for k, v in obj.data.items():
                if kind == "Secret":
                    tgt[k] = _decode_secret_value(v)
                else:
                    tgt[k] = v.encode() if isinstance(v, str) else v


class ProjectedPlugin(_DriverPlugin):
    name = "kubernetes.io/projected"
    driver = "Projected"

    def new_mounter(self, spec, pod, host):
        return ProjectedMounter(spec, pod, host)


class NFSMounter(Mounter):
    def set_up(self) -> None:
        export = "nfs:" + self.spec.source.volume_id  # "server:/path"
        shared = self.host.shared_fs.setdefault(export, {})
        self.host.pod_dir(self.pod.key())[self.spec.name] = shared


class NFSPlugin(_DriverPlugin):
    name = "kubernetes.io/nfs"
    driver = "NFS"

    def new_mounter(self, spec, pod, host):
        return NFSMounter(spec, pod, host)


class LocalMounter(Mounter):
    def can_mount(self) -> Optional[str]:
        # a local PV is node-pinned; mounting from another node is the
        # hard failure the VolumeNode predicate exists to prevent
        # (pkg/volume/local/local.go + predicates.go:1345)
        pv = self.spec.pv
        if pv is not None and pv.node_affinity_terms:
            node = None
            if self.host.api is not None:
                try:
                    node = self.host.api.get("Node", "", self.host.node_name)
                except NotFound:
                    pass
            labels = node.labels if node is not None else {}
            # PV terms are ANDed (util.go:202-214), unlike pod affinity
            if not all(t.matches_labels(labels)
                       for t in pv.node_affinity_terms):
                return (f"local volume {pv.name!r} has a node affinity "
                        f"conflict with node {self.host.node_name!r}")
        return None

    def set_up(self) -> None:
        reason = self.can_mount()
        if reason:
            raise VolumeError(reason)
        path = "local:" + (self.spec.source.volume_id or "/")
        shared = self.host.node_fs.setdefault(path, {})
        self.host.pod_dir(self.pod.key())[self.spec.name] = shared


class LocalPlugin(_DriverPlugin):
    name = "kubernetes.io/local-volume"
    driver = "Local"

    def new_mounter(self, spec, pod, host):
        return LocalMounter(spec, pod, host)


# -------------------------------------------------------- attachable drivers


class BlockDeviceMounter(Mounter):
    """Mount an attached device: refuses when the device has not been
    attached to this node (WaitForAttach semantics, gce_pd/attacher.go)."""

    def can_mount(self) -> Optional[str]:
        src = self.spec.source
        dev = f"{VolumeKind(src.kind).value}:{src.volume_id}"
        node = None
        if self.host.api is not None:
            try:
                node = self.host.api.get("Node", "", self.host.node_name)
            except NotFound:
                pass
        from kubernetes_tpu.controllers.cloudctrl import ATTACHED_ANNOTATION
        attached = set() if node is None else set(filter(
            None, node.annotations.get(ATTACHED_ANNOTATION, "").split(",")))
        if dev not in attached:
            return (f"volume {self.spec.name!r} device {dev} is not "
                    f"attached to node {self.host.node_name!r}")
        return None

    def set_up(self) -> None:
        reason = self.can_mount()
        if reason:
            raise VolumeError(reason)
        src = self.spec.source
        dev = f"{VolumeKind(src.kind).value}:{src.volume_id}"
        shared = self.host.shared_fs.setdefault(dev, {})
        self.host.pod_dir(self.pod.key())[self.spec.name] = shared


class _AttachablePlugin(_KindPlugin):
    attachable = True

    def new_mounter(self, spec, pod, host):
        return BlockDeviceMounter(spec, pod, host)


class GCEPDPlugin(_AttachablePlugin):
    name = "kubernetes.io/gce-pd"
    kind = VolumeKind.GCE_PD


class AWSEBSPlugin(_AttachablePlugin):
    name = "kubernetes.io/aws-ebs"
    kind = VolumeKind.AWS_EBS


class AzureDiskPlugin(_AttachablePlugin):
    name = "kubernetes.io/azure-disk"
    kind = VolumeKind.AZURE_DISK


class NetworkBlockMounter(Mounter):
    """RBD/iSCSI are kubelet-mounted network block devices in v1.7 — no
    controller attach step (no attacher.go in pkg/volume/{rbd,iscsi})."""

    def set_up(self) -> None:
        src = self.spec.source
        dev = f"{VolumeKind(src.kind).value}:{src.volume_id or src.image}"
        shared = self.host.shared_fs.setdefault(dev, {})
        self.host.pod_dir(self.pod.key())[self.spec.name] = shared


class RBDPlugin(_KindPlugin):
    name = "kubernetes.io/rbd"
    kind = VolumeKind.RBD

    def new_mounter(self, spec, pod, host):
        return NetworkBlockMounter(spec, pod, host)


class ISCSIPlugin(_KindPlugin):
    name = "kubernetes.io/iscsi"
    kind = VolumeKind.ISCSI

    def new_mounter(self, spec, pod, host):
        return NetworkBlockMounter(spec, pod, host)


def default_plugins() -> List[VolumePlugin]:
    """ProbeVolumePlugins — the in-tree driver set
    (cmd/kube-controller-manager/app/plugins.go + kubelet's)."""
    return [
        EmptyDirPlugin(), HostPathPlugin(), ConfigMapPlugin(),
        SecretPlugin(), DownwardAPIPlugin(), ProjectedPlugin(),
        NFSPlugin(), LocalPlugin(), GCEPDPlugin(), AWSEBSPlugin(),
        AzureDiskPlugin(), RBDPlugin(), ISCSIPlugin(),
    ]
