"""Volume plugin interface — the in-framework mirror of
pkg/volume/plugins.go.

The reference's contract, kept shape-for-shape:

- ``VolumePlugin``: `GetPluginName`, `CanSupport(spec)`, `NewMounter`,
  `NewUnmounter` (plugins.go:60-103); attachable plugins additionally
  produce an `Attacher`/`Detacher` (pkg/volume/*/attacher.go).
- ``VolumePluginMgr.FindPluginBySpec``: exactly one plugin must claim a
  spec — zero or multiple matches is an error (plugins.go:372-392).
- ``VolumeSpec``: either a direct pod volume or a PersistentVolume
  resolved from a PVC (volume/plugins.go Spec struct).
- ``VolumeHost``: what plugins may touch of the outside world
  (plugins.go:244 VolumeHost interface) — here: the pod-dir filesystem
  (an in-memory dict standing in for /var/lib/kubelet/pods/...), the API
  store (ConfigMap/Secret payloads), and the cloud provider (attach).

Mount results materialize files into `host.pod_dir(pod_key)[volume_name]`
so tests and the kubelet can assert actual content, the way the
reference's fake mounters land files under a tmp dir.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from kubernetes_tpu.api.types import (
    PersistentVolume,
    Pod,
    Volume,
    VolumeKind,
)


class VolumeError(Exception):
    """Mount/attach layer failure (surfaces as a FailedMount pod event)."""


@dataclass
class VolumeSpec:
    """volume/plugins.go Spec: a pod-inline volume OR a bound PV."""

    volume: Optional[Volume] = None
    pv: Optional[PersistentVolume] = None
    read_only: bool = False
    # for PVC-resolved specs: the pod-spec volume name the mount must land
    # under (the pod addresses the volume by ITS name, not the PV's)
    pod_volume_name: str = ""

    @property
    def source(self) -> Volume:
        if self.pv is not None:
            return self.pv.source
        if self.volume is None:
            raise VolumeError("empty VolumeSpec")
        return self.volume

    @property
    def name(self) -> str:
        if self.pod_volume_name:
            return self.pod_volume_name
        if self.volume is not None:
            return self.volume.name
        return self.pv.name if self.pv is not None else ""


class VolumeHost:
    """plugins.go VolumeHost: the kubelet-side world plugins operate in.

    `fs` maps pod_key -> volume_name -> {path: bytes} (the pod volume
    dirs); `node_fs` is the per-node host filesystem HostPath/Local bind
    into; `shared_fs` models remote backends (NFS exports, attached
    disks' content) keyed by backend identity so two nodes mounting the
    same export see the same files.
    """

    def __init__(self, api=None, cloud=None, node_name: str = ""):
        self.api = api
        self.cloud = cloud
        self.node_name = node_name
        self.fs: Dict[str, Dict[str, Dict[str, bytes]]] = {}
        self.node_fs: Dict[str, Dict[str, bytes]] = {}
        self.shared_fs: Dict[str, Dict[str, bytes]] = {}

    def pod_dir(self, pod_key: str) -> Dict[str, Dict[str, bytes]]:
        return self.fs.setdefault(pod_key, {})

    def remove_pod_dir(self, pod_key: str) -> None:
        self.fs.pop(pod_key, None)


class Mounter:
    """volume.Mounter: SetUp materializes the volume for one pod."""

    def __init__(self, spec: VolumeSpec, pod: Pod, host: VolumeHost):
        self.spec = spec
        self.pod = pod
        self.host = host

    def can_mount(self) -> Optional[str]:
        """Pre-mount check (volume.Mounter.CanMount); None = ok, else the
        reason mounting is impossible."""
        return None

    def set_up(self) -> None:
        raise NotImplementedError

    def _target(self) -> Dict[str, bytes]:
        return self.host.pod_dir(self.pod.key()).setdefault(
            self.spec.name, {})


class Unmounter:
    """volume.Unmounter: TearDown removes the pod's view of the volume."""

    def __init__(self, volume_name: str, pod_key: str, host: VolumeHost):
        self.volume_name = volume_name
        self.pod_key = pod_key
        self.host = host

    def tear_down(self) -> None:
        self.host.pod_dir(self.pod_key).pop(self.volume_name, None)


class Attacher:
    """volume.Attacher (pkg/volume/*/attacher.go): node-level attach +
    wait-for-attach. Device identity is "<Kind>:<volume_id>", matching the
    attach-detach controller's node-annotation record
    (controllers/cloudctrl.py ATTACHED_ANNOTATION)."""

    def __init__(self, plugin: "VolumePlugin", host: VolumeHost):
        self.plugin = plugin
        self.host = host

    def attach(self, spec: VolumeSpec, node_name: str) -> str:
        src = spec.source
        dev = f"{VolumeKind(src.kind).value}:{src.volume_id}"
        if self.host.cloud is not None:
            self.host.cloud.attach_disk(src.volume_id, node_name)
        return dev

    def volumes_are_attached(self, devs: List[str], node) -> List[str]:
        """Subset of devs recorded attached on the node object."""
        from kubernetes_tpu.controllers.cloudctrl import ATTACHED_ANNOTATION
        current = set(filter(None, node.annotations.get(
            ATTACHED_ANNOTATION, "").split(",")))
        return [d for d in devs if d in current]


class Detacher:
    def __init__(self, plugin: "VolumePlugin", host: VolumeHost):
        self.plugin = plugin
        self.host = host

    def detach(self, dev: str, node_name: str) -> None:
        if self.host.cloud is not None:
            vol_id = dev.split(":", 1)[1] if ":" in dev else dev
            self.host.cloud.detach_disk(vol_id, node_name)


class VolumePlugin:
    """Base plugin; concrete drivers override name/can_support/mounters."""

    name = ""
    attachable = False  # requires attach before mount (EBS/GCE-PD/...)

    def can_support(self, spec: VolumeSpec) -> bool:
        raise NotImplementedError

    def new_mounter(self, spec: VolumeSpec, pod: Pod,
                    host: VolumeHost) -> Mounter:
        raise NotImplementedError

    def new_unmounter(self, volume_name: str, pod_key: str,
                      host: VolumeHost) -> Unmounter:
        return Unmounter(volume_name, pod_key, host)

    def new_attacher(self, host: VolumeHost) -> Attacher:
        if not self.attachable:
            raise VolumeError(f"plugin {self.name} is not attachable")
        return Attacher(self, host)

    def new_detacher(self, host: VolumeHost) -> Detacher:
        if not self.attachable:
            raise VolumeError(f"plugin {self.name} is not attachable")
        return Detacher(self, host)


class VolumePluginManager:
    """plugins.go VolumePluginMgr: registry + FindPluginBySpec with the
    no-match / multi-match error semantics (plugins.go:372-392)."""

    def __init__(self, plugins: Optional[List[VolumePlugin]] = None):
        self._plugins: Dict[str, VolumePlugin] = {}
        for p in plugins or []:
            self.register(p)

    def register(self, plugin: VolumePlugin) -> None:
        if plugin.name in self._plugins:
            raise VolumeError(
                f"volume plugin {plugin.name!r} registered twice")
        self._plugins[plugin.name] = plugin

    def find_plugin_by_spec(self, spec: VolumeSpec) -> VolumePlugin:
        matches = [p for p in self._plugins.values() if p.can_support(spec)]
        if not matches:
            raise VolumeError(
                f"no volume plugin matched spec {spec.name!r}")
        if len(matches) > 1:
            raise VolumeError(
                f"multiple volume plugins matched spec {spec.name!r}: "
                + ", ".join(sorted(p.name for p in matches)))
        return matches[0]

    def find_plugin_by_name(self, name: str) -> VolumePlugin:
        if name not in self._plugins:
            raise VolumeError(f"no volume plugin named {name!r}")
        return self._plugins[name]

    def plugin_names(self) -> List[str]:
        return sorted(self._plugins)


def resolve_spec(volume: Volume, api, namespace: str) -> VolumeSpec:
    """Turn a pod-spec volume into a mountable VolumeSpec, dereferencing a
    PVC through its bound PV (volume/plugins.go CreateVolumeSpec in the
    desired-state populator)."""
    if VolumeKind(volume.kind) is not VolumeKind.PVC:
        return VolumeSpec(volume=volume, read_only=volume.read_only)
    if api is None:
        raise VolumeError(f"PVC volume {volume.name!r} needs an API host")
    from kubernetes_tpu.server.apiserver_lite import NotFound
    try:
        pvc = api.get("PersistentVolumeClaim", namespace, volume.volume_id)
    except NotFound:
        raise VolumeError(
            f"PVC {namespace}/{volume.volume_id} not found") from None
    if not pvc.volume_name:
        raise VolumeError(
            f"PVC {namespace}/{volume.volume_id} is not bound yet")
    try:
        pv = api.get("PersistentVolume", "", pvc.volume_name)
    except NotFound:
        raise VolumeError(
            f"PV {pvc.volume_name} (bound to PVC "
            f"{namespace}/{volume.volume_id}) not found") from None
    return VolumeSpec(pv=pv, read_only=volume.read_only,
                      pod_volume_name=volume.name)
