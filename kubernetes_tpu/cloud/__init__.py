from kubernetes_tpu.cloud.provider import (  # noqa: F401
    CloudProvider,
    FakeCloud,
    GCELikeCloud,
    AWSLikeCloud,
    get_provider,
    register_provider,
)
