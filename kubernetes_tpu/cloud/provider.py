"""Cloud provider interface + providers.

Mirror of pkg/cloudprovider/cloud.go's Interface: Instances / Zones /
LoadBalancer / Routes (the slices the service and route controllers consume)
with the provider registry of pkg/cloudprovider/plugins.go. The reference
ships 9 providers (aws, azure, cloudstack, gce, openstack, ovirt, photon,
rackspace, vsphere) whose value is API-client plumbing against real clouds;
here the contract is carried by FakeCloud (the reference's
pkg/cloudprovider/providers/fake used by every controller test) plus two
named providers exercising provider-specific behavior the controllers can
observe (zone layout, LB naming, route semantics)."""

from __future__ import annotations

import threading
from kubernetes_tpu.analysis import lockcheck
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple


@dataclass
class LoadBalancerStatus:
    ingress_ip: str = ""


@dataclass
class Route:
    name: str
    target_node: str
    destination_cidr: str


class CloudProvider:
    """cloudprovider.Interface: nil-able sub-interfaces are modeled as
    has_*() capability flags (Interface() (T, bool) in Go)."""

    provider_name = "abstract"

    # Instances
    def has_instances(self) -> bool:
        return False

    def node_addresses(self, node_name: str) -> List[str]:
        raise NotImplementedError

    def instance_exists(self, node_name: str) -> bool:
        raise NotImplementedError

    # Zones
    def has_zones(self) -> bool:
        return False

    def zone_for(self, node_name: str) -> Tuple[str, str]:  # (zone, region)
        raise NotImplementedError

    # LoadBalancer
    def has_load_balancer(self) -> bool:
        return False

    def ensure_load_balancer(self, service_key: str,
                             node_names: List[str]) -> LoadBalancerStatus:
        raise NotImplementedError

    def update_load_balancer(self, service_key: str,
                             node_names: List[str]) -> None:
        raise NotImplementedError

    def ensure_load_balancer_deleted(self, service_key: str) -> None:
        raise NotImplementedError

    # Routes
    def has_routes(self) -> bool:
        return False

    def list_routes(self) -> List[Route]:
        raise NotImplementedError

    def create_route(self, route: Route) -> None:
        raise NotImplementedError

    def delete_route(self, name: str) -> None:
        raise NotImplementedError

    # -- Disks (the volume-attacher surface: providers/{gce,aws,azure}
    # AttachDisk/DetachDisk/DisksAreAttached, consumed by
    # volumes/plugins.py Attacher and the attach-detach controller)

    def has_disks(self) -> bool:
        return False  # capability flag: absent sub-interface = False,
        # like has_instances/has_zones/has_load_balancer/has_routes

    def create_disk(self, volume_id: str, size_gb: int = 10) -> None:
        raise NotImplementedError

    def delete_disk(self, volume_id: str) -> None:
        raise NotImplementedError

    def attach_disk(self, volume_id: str, node_name: str) -> None:
        raise NotImplementedError

    def detach_disk(self, volume_id: str, node_name: str) -> None:
        raise NotImplementedError

    def disks_attached(self, node_name: str) -> List[str]:
        raise NotImplementedError


class DiskError(Exception):
    """Attach/detach failure (multi-attach, unknown disk, node limit)."""


class FakeCloud(CloudProvider):
    """pkg/cloudprovider/providers/fake: records calls, serves canned data."""

    provider_name = "fake"

    def __init__(self):
        self._lock = lockcheck.make_lock("FakeCloud._lock")
        self.instances: Dict[str, List[str]] = {}
        self.zones: Dict[str, Tuple[str, str]] = {}
        self.balancers: Dict[str, LoadBalancerStatus] = {}
        self.balancer_nodes: Dict[str, List[str]] = {}
        self.routes: Dict[str, Route] = {}
        self.calls: List[str] = []
        self._next_ip = 1
        self.disks: Dict[str, int] = {}  # volume_id -> size_gb
        # volume_id -> (zone, region); what PersistentVolumeLabel admission
        # reads (plugin/pkg/admission/persistentvolume/label)
        self.disk_zones: Dict[str, Tuple[str, str]] = {}
        self.attachments: Dict[str, str] = {}  # volume_id -> node
        # per-node attachable-disk ceiling (the cloud-side analog of the
        # MaxPDVolumeCount predicate defaults)
        self.max_disks_per_node = 16

    # Instances
    def has_instances(self) -> bool:
        return True

    def add_instance(self, name: str, addresses: Optional[List[str]] = None,
                     zone: str = "zone-a", region: str = "region-1") -> None:
        self.instances[name] = addresses or [f"192.168.0.{len(self.instances)+1}"]
        self.zones[name] = (zone, region)

    def node_addresses(self, node_name: str) -> List[str]:
        with self._lock:
            self.calls.append("node-addresses")
            return self.instances.get(node_name, [])

    def instance_exists(self, node_name: str) -> bool:
        with self._lock:
            self.calls.append("instance-exists")
            return node_name in self.instances

    # Zones
    def has_zones(self) -> bool:
        return True

    def zone_for(self, node_name: str) -> Tuple[str, str]:
        return self.zones.get(node_name, ("zone-a", "region-1"))

    # LoadBalancer
    def has_load_balancer(self) -> bool:
        return True

    def ensure_load_balancer(self, service_key, node_names):
        with self._lock:
            self.calls.append("ensure-lb")
            st = self.balancers.get(service_key)
            if st is None:
                st = LoadBalancerStatus(f"172.24.0.{self._next_ip}")
                self._next_ip += 1
                self.balancers[service_key] = st
            self.balancer_nodes[service_key] = sorted(node_names)
            return st

    def update_load_balancer(self, service_key, node_names):
        with self._lock:
            self.calls.append("update-lb")
            self.balancer_nodes[service_key] = sorted(node_names)

    def ensure_load_balancer_deleted(self, service_key):
        with self._lock:
            self.calls.append("delete-lb")
            self.balancers.pop(service_key, None)
            self.balancer_nodes.pop(service_key, None)

    # Routes
    def has_routes(self) -> bool:
        return True

    def list_routes(self):
        with self._lock:
            return list(self.routes.values())

    def create_route(self, route: Route) -> None:
        with self._lock:
            self.calls.append("create-route")
            self.routes[route.name] = route

    def delete_route(self, name: str) -> None:
        with self._lock:
            self.calls.append("delete-route")
            self.routes.pop(name, None)

    # Disks
    def has_disks(self) -> bool:
        return True

    def create_disk(self, volume_id: str, size_gb: int = 10,
                    zone: str = "zone-a", region: str = "region-1") -> None:
        with self._lock:
            self.disks[volume_id] = size_gb
            self.disk_zones[volume_id] = (zone, region)

    def disk_zone(self, volume_id: str) -> Optional[Tuple[str, str]]:
        """Where the disk lives — the cloud's authoritative answer the PV
        label admission stamps onto PVs. None for a disk this cloud never
        created (the reference plugin errors rather than fabricate a
        zone)."""
        with self._lock:
            return self.disk_zones.get(volume_id)

    def delete_disk(self, volume_id: str) -> None:
        with self._lock:
            if volume_id in self.attachments:
                raise DiskError(
                    f"disk {volume_id!r} is attached to "
                    f"{self.attachments[volume_id]!r}")
            self.disks.pop(volume_id, None)
            self.disk_zones.pop(volume_id, None)

    def _validate_attach_locked(self, volume_id: str) -> None:
        """Flavor hook, called UNDER self._lock so existence checks cannot
        race delete_disk (OpenStack's no-lazy-provisioning rule)."""

    def attach_disk(self, volume_id: str, node_name: str) -> None:
        """Single-writer attach: attaching a disk already on another node
        fails (the multi-attach error every block-store cloud raises);
        re-attach to the same node is idempotent."""
        with self._lock:
            self.calls.append("attach-disk")
            self._validate_attach_locked(volume_id)
            self.disks.setdefault(volume_id, 10)  # lazily provisioned
            cur = self.attachments.get(volume_id)
            if cur is not None and cur != node_name:
                raise DiskError(
                    f"disk {volume_id!r} is already attached to {cur!r}")
            if cur is None and sum(
                    1 for n in self.attachments.values()
                    if n == node_name) >= self.max_disks_per_node:
                raise DiskError(
                    f"node {node_name!r} is at its attachable-disk limit")
            self.attachments[volume_id] = node_name

    def detach_disk(self, volume_id: str, node_name: str) -> None:
        with self._lock:
            self.calls.append("detach-disk")
            if self.attachments.get(volume_id) == node_name:
                del self.attachments[volume_id]

    def disks_attached(self, node_name: str) -> List[str]:
        with self._lock:
            return sorted(v for v, n in self.attachments.items()
                          if n == node_name)


class GCELikeCloud(FakeCloud):
    """GCE-shaped behavior (providers/gce): per-zone instance groups, LB IPs
    from a regional pool, route names prefixed by cluster."""

    provider_name = "gce-like"

    def __init__(self, cluster: str = "ktpu"):
        super().__init__()
        self.cluster = cluster

    def ensure_load_balancer(self, service_key, node_names):
        st = super().ensure_load_balancer(service_key, node_names)
        st.ingress_ip = "35.0.0." + st.ingress_ip.rsplit(".", 1)[1]
        return st

    def create_route(self, route: Route) -> None:
        route = Route(f"{self.cluster}-{route.name}", route.target_node,
                      route.destination_cidr)
        super().create_route(route)


class AWSLikeCloud(FakeCloud):
    """AWS-shaped behavior (providers/aws): hostname-style LB ingress."""

    provider_name = "aws-like"

    def ensure_load_balancer(self, service_key, node_names):
        st = super().ensure_load_balancer(service_key, node_names)
        slug = service_key.replace("/", "-")
        st.ingress_ip = f"{slug}.elb.region-1.example.amazonaws.com"
        return st


class AzureLikeCloud(FakeCloud):
    """Azure-shaped behavior (providers/azure): LB frontend IPs from a
    resource-group pool, tight default disk-per-node limit (the DS-series
    data-disk caps the AzureDisk MaxPD filter mirrors)."""

    provider_name = "azure-like"

    def __init__(self, resource_group: str = "ktpu-rg"):
        super().__init__()
        self.resource_group = resource_group
        self.max_disks_per_node = 8

    def ensure_load_balancer(self, service_key, node_names):
        st = super().ensure_load_balancer(service_key, node_names)
        st.ingress_ip = "20.0.0." + st.ingress_ip.rsplit(".", 1)[1]
        return st


class OpenStackLikeCloud(FakeCloud):
    """OpenStack-shaped behavior (providers/openstack): Cinder volumes
    must be created before attach (no lazy provisioning), Neutron-style
    floating IPs."""

    provider_name = "openstack-like"

    def _validate_attach_locked(self, volume_id: str) -> None:
        if volume_id not in self.disks:
            raise DiskError(
                f"cinder volume {volume_id!r} does not exist")

    def ensure_load_balancer(self, service_key, node_names):
        st = super().ensure_load_balancer(service_key, node_names)
        st.ingress_ip = "10.250.0." + st.ingress_ip.rsplit(".", 1)[1]
        return st


class VSphereLikeCloud(FakeCloud):
    """vSphere-shaped behavior (providers/vsphere): no cloud
    load-balancer or routes — instances/zones/disks only, like the
    reference driver."""

    provider_name = "vsphere-like"

    def has_load_balancer(self) -> bool:
        return False

    def has_routes(self) -> bool:
        return False


_REGISTRY: Dict[str, Callable[[], CloudProvider]] = {
    "fake": FakeCloud,
    "gce-like": GCELikeCloud,
    "aws-like": AWSLikeCloud,
    "azure-like": AzureLikeCloud,
    "openstack-like": OpenStackLikeCloud,
    "vsphere-like": VSphereLikeCloud,
}


def register_provider(name: str, factory: Callable[[], CloudProvider]) -> None:
    """cloudprovider.RegisterCloudProvider (plugins.go)."""
    _REGISTRY[name] = factory


def get_provider(name: str) -> CloudProvider:
    try:
        return _REGISTRY[name]()
    except KeyError:
        raise KeyError(f"unknown cloud provider {name!r}; registered: "
                       f"{sorted(_REGISTRY)}") from None
