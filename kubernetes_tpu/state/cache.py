"""Scheduler cache: authoritative in-memory cluster state incl. assumed pods.

TPU-native analog of schedulerCache (reference:
plugin/pkg/scheduler/schedulercache/cache.go:44-386). Semantics preserved:

- AssumePod (cache.go:109): optimistically add a just-scheduled pod to its
  chosen node *before* the bind API call returns, so the next scheduling
  decision sees it. Unblocks pipelining.
- FinishBinding (cache.go:130): start the TTL clock; if the informer never
  confirms the bind (apiserver write lost), cleanup_assumed (cache.go:355)
  expires the assumption and the pod's resources are released — the
  self-healing path.
- ForgetPod (cache.go:154): bind failed synchronously; undo immediately.
- AddPod/UpdatePod/RemovePod (cache.go:214/248/275): informer-confirmed
  transitions; a confirmed Add of an assumed pod just clears the deadline.
- Add/Update/RemoveNode (cache.go:304/316/328).
- UpdateNodeNameToInfoMap (cache.go:79): generation-diffed snapshot — here it
  feeds the tensor snapshot's delta refresh instead of cloning Go structs.

Thread-safety: a single lock, like the reference's mutex (cache.go:50). The
engine runs scheduling on one thread (matching the reference's single
scheduleOne goroutine, scheduler.go:253) with informer updates arriving from
the watch thread.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from kubernetes_tpu.api.types import Node, Pod
from kubernetes_tpu.state.node_info import NodeInfo


class _PodState:
    __slots__ = ("pod", "assumed", "deadline", "binding_finished")

    def __init__(self, pod: Pod):
        self.pod = pod
        self.assumed = False
        self.deadline: Optional[float] = None
        self.binding_finished = False


def _pod_has_affinity(pod: Pod) -> bool:
    return pod.has_pod_affinity()


class SchedulerCache:
    def __init__(self, ttl_seconds: float = 30.0, now: Callable[[], float] = time.monotonic):
        self._ttl = ttl_seconds
        self._now = now
        self._lock = threading.Lock()
        self._pod_states: Dict[str, _PodState] = {}
        self._nodes: Dict[str, NodeInfo] = {}
        # affinity-churn sequence: bumped once per (anti-)affinity-carrying
        # pod entering or leaving any NodeInfo (assume, confirm-move,
        # foreign add/remove, TTL expiry, forget). The wave engine's cached
        # AffinityData folds its OWN assumes into this counter, so
        # aff_seq != expected means a FOREIGN mutation invalidated the
        # static topology arrays (ISSUE 3). Confirming our own assume in
        # place mutates no NodeInfo and does not bump.
        self.aff_seq = 0

    # ------------------------------------------------------------------ pods

    def assume_pod(self, pod: Pod) -> None:
        key = pod.key()
        with self._lock:
            if key in self._pod_states:
                raise KeyError(f"pod {key} is already in the cache")
            self._add_pod_locked(pod)
            st = _PodState(pod)
            st.assumed = True
            self._pod_states[key] = st

    def assume_pods_bulk(self, placements, derived) -> None:
        """AssumePod for a whole batch under one lock acquisition.
        placements = [(pod, class_index)] with pod.node_name already set;
        derived = per-class [(Resource, nonzero_cpu, nonzero_mem, ports)].
        Semantics identical to assume_pod per pod, in order."""
        with self._lock:
            for pod, c in placements:
                key = pod.key()
                if key in self._pod_states:
                    raise KeyError(f"pod {key} is already in the cache")
                req, ncpu, nmem, ports = derived[c]
                info = self._nodes.get(pod.node_name)
                if info is None:
                    info = NodeInfo()
                    self._nodes[pod.node_name] = info
                info.add_pod_precomputed(pod, req, ncpu, nmem, ports)
                if _pod_has_affinity(pod):
                    self.aff_seq += 1
                st = _PodState(pod)
                st.assumed = True
                self._pod_states[key] = st

    def assume_pods_grouped(self, groups) -> Dict[str, NodeInfo]:
        """AssumePod for a whole wave under one lock, columnar: groups =
        [(node_name, pods, req, ncpu, nmem, ports)] where each entry is a
        run of spec-equal pods (pod.node_name already set) headed to ONE
        node. One scaled NodeInfo update per (node, class) group instead of
        one object walk per pod — semantics identical to assume_pod per
        pod, in group order. Returns the touched NodeInfos by name so the
        caller can sync snapshot generation bookkeeping."""
        touched: Dict[str, NodeInfo] = {}
        with self._lock:
            states = self._pod_states
            nodes_get = self._nodes.get
            mk = _PodState
            for node_name, pods, req, ncpu, nmem, ports in groups:
                info = nodes_get(node_name)
                if info is None:
                    info = NodeInfo()
                    self._nodes[node_name] = info
                info.add_pods_same_class(pods, req, ncpu, nmem, ports)
                if pods and _pod_has_affinity(pods[0]):
                    self.aff_seq += len(pods)
                touched[node_name] = info
                for pod in pods:
                    key = pod.key()
                    if key in states:
                        raise KeyError(f"pod {key} is already in the cache")
                    st = mk(pod)
                    st.assumed = True
                    states[key] = st
        return touched

    def add_pods_bulk(self, pods: List[Pod]) -> List[str]:
        """Informer-confirmed adds for a batch under ONE lock (the columnar
        watch drain of a bind storm). Per-pod semantics identical to
        add_pod(); returns the names of nodes whose NodeInfo was MUTATED
        (confirming our own assume on the same node mutates nothing — the
        common case — so the caller's targeted-refresh hint stays empty on
        a pure confirmation stream)."""
        touched: List[str] = []
        with self._lock:
            states = self._pod_states
            for pod in pods:
                key = pod.key()
                st = states.get(key)
                if st is not None and st.assumed:
                    if st.pod.node_name != pod.node_name:
                        self._remove_pod_locked(st.pod)
                        self._add_pod_locked(pod)
                        touched.append(st.pod.node_name)
                        touched.append(pod.node_name)
                    st.pod = pod
                    st.assumed = False
                    st.deadline = None
                elif st is None:
                    self._add_pod_locked(pod)
                    states[key] = _PodState(pod)
                    touched.append(pod.node_name)
        return touched

    def finish_binding(self, pod: Pod) -> None:
        key = pod.key()
        with self._lock:
            st = self._pod_states.get(key)
            if st is None or not st.assumed:
                return
            st.binding_finished = True
            st.deadline = self._now() + self._ttl

    def finish_bindings_bulk(self, pods: List[Pod],
                             keys: Optional[List[str]] = None) -> None:
        """FinishBinding for a batch under one lock; one clock read. `keys`
        lets the caller share already-computed pod keys."""
        deadline = self._now() + self._ttl
        if keys is None:
            keys = [pod.key() for pod in pods]
        with self._lock:
            get = self._pod_states.get
            for key in keys:
                st = get(key)
                if st is None or not st.assumed:
                    continue
                st.binding_finished = True
                st.deadline = deadline

    def forget_pod(self, pod: Pod) -> None:
        key = pod.key()
        with self._lock:
            st = self._pod_states.get(key)
            if st is None:
                return
            if st.pod.node_name != pod.node_name and st.pod.node_name != "":
                # the reference errors on node mismatch (cache.go:161); we
                # tolerate and remove by the cached location
                pass
            if st.assumed:
                self._remove_pod_locked(st.pod)
                del self._pod_states[key]

    def forget_pods_bulk(self, pods: List[Pod]) -> None:
        """ForgetPod for a whole group under ONE lock acquisition — the
        atomic-rollback half of gang scheduling (ISSUE 5): a below-quorum
        or fence-rolled-back gang releases every member's assumed capacity
        in one pass, so no reader interleaves with a half-rolled-back
        gang. Per-pod semantics identical to forget_pod, in order."""
        with self._lock:
            states = self._pod_states
            for pod in pods:
                st = states.get(pod.key())
                if st is not None and st.assumed:
                    self._remove_pod_locked(st.pod)
                    del states[pod.key()]

    def add_pod(self, pod: Pod) -> None:
        """Informer-confirmed pod add (cache.go:214)."""
        key = pod.key()
        with self._lock:
            st = self._pod_states.get(key)
            if st is not None and st.assumed:
                if st.pod.node_name != pod.node_name:
                    # scheduler decision overridden (e.g. another scheduler);
                    # move the pod (cache.go:224-232 updatePod path)
                    self._remove_pod_locked(st.pod)
                    self._add_pod_locked(pod)
                st.pod = pod
                st.assumed = False
                st.deadline = None
            elif st is None:
                self._add_pod_locked(pod)
                self._pod_states[key] = _PodState(pod)

    def update_pod(self, old: Pod, new: Pod) -> None:
        with self._lock:
            st = self._pod_states.get(old.key())
            if st is None:
                self._add_pod_locked(new)
                self._pod_states[new.key()] = _PodState(new)
                return
            self._remove_pod_locked(st.pod)
            self._add_pod_locked(new)
            st.pod = new

    def remove_pod(self, pod: Pod) -> None:
        key = pod.key()
        with self._lock:
            st = self._pod_states.pop(key, None)
            if st is not None:
                self._remove_pod_locked(st.pod)

    def is_assumed(self, pod_key: str) -> bool:
        with self._lock:
            st = self._pod_states.get(pod_key)
            return bool(st and st.assumed)

    def pod_count(self) -> int:
        with self._lock:
            return len(self._pod_states)

    # ----------------------------------------------------------------- nodes

    def add_node(self, node: Node) -> None:
        with self._lock:
            info = self._nodes.get(node.name)
            if info is None:
                info = NodeInfo()
                self._nodes[node.name] = info
            info.set_node(node)

    def update_node(self, node: Node) -> None:
        self.add_node(node)

    def remove_node(self, name: str) -> None:
        with self._lock:
            info = self._nodes.pop(name, None)
            # the reference keeps the entry if pods remain (cache.go:334-339);
            # we drop it — orphaned pods re-add a nodeless NodeInfo below
            if info is not None and info.pods:
                stub = NodeInfo()
                for p in info.pods:
                    stub.add_pod(p)
                    if _pod_has_affinity(p):
                        # the pods' NodeInfo (and its node object) moved —
                        # cached topology arrays resolved domains through it
                        self.aff_seq += 1
                self._nodes[name] = stub

    # -------------------------------------------------------------- snapshot

    def node_infos(self) -> Dict[str, NodeInfo]:
        """Live references (caller must treat as read-only, or hold no pointer
        across mutations). The tensor snapshot reads generations from these —
        the moral equivalent of UpdateNodeNameToInfoMap (cache.go:79)."""
        with self._lock:
            return dict(self._nodes)

    def snapshot_infos(self) -> Dict[str, NodeInfo]:
        with self._lock:
            return {k: v.clone_shallow() for k, v in self._nodes.items()}

    # -------------------------------------------------------------- expiry

    def cleanup_assumed(self) -> List[str]:
        """Expire assumed pods whose bind was never confirmed within TTL
        (cache.go:355 cleanupAssumedPods). Returns expired pod keys."""
        expired = []
        now = self._now()
        with self._lock:
            for key, st in list(self._pod_states.items()):
                if st.assumed and st.binding_finished and st.deadline is not None \
                        and now >= st.deadline:
                    self._remove_pod_locked(st.pod)
                    del self._pod_states[key]
                    expired.append(key)
        return expired

    # -------------------------------------------------------------- internal

    def _add_pod_locked(self, pod: Pod) -> None:
        info = self._nodes.get(pod.node_name)
        if info is None:
            info = NodeInfo()
            self._nodes[pod.node_name] = info
        info.add_pod(pod)
        if _pod_has_affinity(pod):
            self.aff_seq += 1

    def _remove_pod_locked(self, pod: Pod) -> None:
        info = self._nodes.get(pod.node_name)
        if info is not None:
            info.remove_pod(pod)
            if _pod_has_affinity(pod):
                self.aff_seq += 1
