"""Scheduler cache: authoritative in-memory cluster state incl. assumed pods.

TPU-native analog of schedulerCache (reference:
plugin/pkg/scheduler/schedulercache/cache.go:44-386). Semantics preserved:

- AssumePod (cache.go:109): optimistically add a just-scheduled pod to its
  chosen node *before* the bind API call returns, so the next scheduling
  decision sees it. Unblocks pipelining.
- FinishBinding (cache.go:130): start the TTL clock; if the informer never
  confirms the bind (apiserver write lost), cleanup_assumed (cache.go:355)
  expires the assumption and the pod's resources are released — the
  self-healing path.
- ForgetPod (cache.go:154): bind failed synchronously; undo immediately.
- AddPod/UpdatePod/RemovePod (cache.go:214/248/275): informer-confirmed
  transitions; a confirmed Add of an assumed pod just clears the deadline.
- Add/Update/RemoveNode (cache.go:304/316/328).
- UpdateNodeNameToInfoMap (cache.go:79): generation-diffed snapshot — here it
  feeds the tensor snapshot's delta refresh instead of cloning Go structs.

Thread-safety: a single lock, like the reference's mutex (cache.go:50). The
engine runs scheduling on one thread (matching the reference's single
scheduleOne goroutine, scheduler.go:253) with informer updates arriving from
the watch thread.
"""

from __future__ import annotations

import threading
from kubernetes_tpu.analysis import lockcheck
import time
from typing import Callable, Dict, List, Optional

from kubernetes_tpu.api.types import Node, Pod
from kubernetes_tpu.state.node_info import NodeInfo


class _PodState:
    __slots__ = ("pod", "assumed", "deadline", "binding_finished")

    def __init__(self, pod: Pod):
        self.pod = pod
        self.assumed = False
        self.deadline: Optional[float] = None
        self.binding_finished = False


class SchedulerCache:
    # how many affinity-churn events the Protean patch log retains; a
    # consumer further behind than this rebuilds wholesale (ISSUE 8)
    AFF_LOG_MAX = 8192

    def __init__(self, ttl_seconds: float = 30.0, now: Callable[[], float] = time.monotonic):
        self._ttl = ttl_seconds
        self._now = now
        self._lock = lockcheck.make_lock("SchedulerCache._lock")
        self._pod_states: Dict[str, _PodState] = {}
        self._nodes: Dict[str, NodeInfo] = {}
        # occupancy-churn sequence: bumped once per pod entering or leaving
        # any NodeInfo (assume, confirm-move, foreign add/remove, TTL
        # expiry, forget). The wave engine's cached AffinityData folds its
        # OWN assumes into this counter, so aff_seq != expected means a
        # FOREIGN mutation may have invalidated the static topology arrays
        # (ISSUE 3). Confirming our own assume in place mutates no NodeInfo
        # and does not bump. Widened from affinity-carrying pods to ALL
        # pods in ISSUE 8: a PLAIN pod whose labels match a pending class's
        # anti-affinity selector is a new forbidden-domain source the old
        # keying silently missed; the Protean patch log below keeps the
        # widened invalidation from degrading into wholesale rebuilds.
        self.aff_seq = 0
        # Protean patch log (ISSUE 8, PAPERS.md §Protean: key caches on
        # exactly what invalidates them): every aff_seq bump appends
        # (seq_after, pod, node_name, delta) with delta +1 for a pod
        # entering a NodeInfo and -1 for one leaving, so a consumer whose
        # expectation fell behind can PATCH the exact rows foreign churn
        # touched instead of rebuilding its topology arrays wholesale.
        # delta == 0 is the "structure moved under this pod" sentinel
        # (node removed: the pod's NodeInfo became a tombstone stub under
        # the same name — a no-op for label-derived views, since the
        # snapshot keeps the row and its label content in place).
        # Bounded ring: _aff_log_start is the lowest seq whose delta is
        # still retained; consumers behind it must rebuild.
        self._aff_log: List[tuple] = []
        self._aff_log_start = 0
        # exact count of resident pods carrying affinity/anti-affinity
        # terms (ISSUE 17): the fast lane's eligibility gate — an
        # EXISTING pod's anti-affinity can forbid a new plain pod
        # (k8s 1.8 InterPodAffinityPredicate symmetry), so the fast lane
        # only runs when this is zero. Maintained in _aff_event_locked,
        # which every pod enter/leave already routes through.
        self._aff_pods = 0

    # ---------------------------------------------------------- churn log

    def _aff_event_locked(self, pod: Pod, node_name: str, delta: int) -> None:
        """Bump aff_seq AND record what moved (caller holds the lock)."""
        lockcheck.assert_held(self._lock, "_aff_event_locked")
        self.aff_seq += 1
        if delta != 0 and pod.has_pod_affinity():
            self._aff_pods += delta
        log = self._aff_log
        log.append((self.aff_seq, pod, node_name, delta))
        # amortized trim: shifting per append would be O(ring) on the
        # 20k-assumes/s path; trimming at 2x keeps memory bounded at one
        # extra ring while the shift cost amortizes to O(1) per event
        if len(log) >= 2 * self.AFF_LOG_MAX:
            del log[:len(log) - self.AFF_LOG_MAX]

    def aff_events_since(self, seq: int) -> Optional[List[tuple]]:
        """The (seq, pod, node_name, delta) events after `seq`, oldest
        first — or None when the bounded ring no longer covers the gap
        (the consumer fell too far behind and must rebuild). Sequences are
        consecutive integers, so coverage is a length check, not a scan."""
        with self._lock:
            behind = self.aff_seq - seq
            if behind <= 0:
                return []
            if behind > len(self._aff_log):
                return None
            return list(self._aff_log[len(self._aff_log) - behind:])

    # ------------------------------------------------------------------ pods

    def assume_pod(self, pod: Pod) -> None:
        key = pod.key()
        with self._lock:
            if key in self._pod_states:
                raise KeyError(f"pod {key} is already in the cache")
            self._add_pod_locked(pod)
            st = _PodState(pod)
            st.assumed = True
            self._pod_states[key] = st

    def assume_pods_bulk(self, placements, derived) -> None:
        """AssumePod for a whole batch under one lock acquisition.
        placements = [(pod, class_index)] with pod.node_name already set;
        derived = per-class [(Resource, nonzero_cpu, nonzero_mem, ports)].
        Semantics identical to assume_pod per pod, in order."""
        with self._lock:
            for pod, c in placements:
                key = pod.key()
                if key in self._pod_states:
                    raise KeyError(f"pod {key} is already in the cache")
                req, ncpu, nmem, ports = derived[c]
                info = self._nodes.get(pod.node_name)
                if info is None:
                    info = NodeInfo()
                    self._nodes[pod.node_name] = info
                info.add_pod_precomputed(pod, req, ncpu, nmem, ports)
                self._aff_event_locked(pod, pod.node_name, 1)
                st = _PodState(pod)
                st.assumed = True
                self._pod_states[key] = st

    def assume_pods_grouped(self, groups) -> Dict[str, NodeInfo]:
        """AssumePod for a whole wave under one lock, columnar: groups =
        [(node_name, pods, req, ncpu, nmem, ports)] where each entry is a
        run of spec-equal pods (pod.node_name already set) headed to ONE
        node. One scaled NodeInfo update per (node, class) group instead of
        one object walk per pod — semantics identical to assume_pod per
        pod, in group order. Returns the touched NodeInfos by name so the
        caller can sync snapshot generation bookkeeping."""
        touched: Dict[str, NodeInfo] = {}
        with self._lock:
            states = self._pod_states
            nodes_get = self._nodes.get
            mk = _PodState
            for node_name, pods, req, ncpu, nmem, ports in groups:
                info = nodes_get(node_name)
                if info is None:
                    info = NodeInfo()
                    self._nodes[node_name] = info
                info.add_pods_same_class(pods, req, ncpu, nmem, ports)
                for pod in pods:
                    self._aff_event_locked(pod, node_name, 1)
                touched[node_name] = info
                for pod in pods:
                    key = pod.key()
                    if key in states:
                        raise KeyError(f"pod {key} is already in the cache")
                    st = mk(pod)
                    st.assumed = True
                    states[key] = st
        return touched

    def add_pods_bulk(self, pods: List[Pod]) -> List[str]:
        """Informer-confirmed adds for a batch under ONE lock (the columnar
        watch drain of a bind storm). Per-pod semantics identical to
        add_pod(); returns the names of nodes whose NodeInfo was MUTATED
        (confirming our own assume on the same node mutates nothing — the
        common case — so the caller's targeted-refresh hint stays empty on
        a pure confirmation stream)."""
        touched: List[str] = []
        with self._lock:
            states = self._pod_states
            for pod in pods:
                key = pod.key()
                st = states.get(key)
                if st is not None and st.assumed:
                    if st.pod.node_name != pod.node_name:
                        self._remove_pod_locked(st.pod)
                        self._add_pod_locked(pod)
                        touched.append(st.pod.node_name)
                        touched.append(pod.node_name)
                    st.pod = pod
                    st.assumed = False
                    st.deadline = None
                elif st is None:
                    self._add_pod_locked(pod)
                    states[key] = _PodState(pod)
                    touched.append(pod.node_name)
        return touched

    def finish_binding(self, pod: Pod) -> None:
        key = pod.key()
        with self._lock:
            st = self._pod_states.get(key)
            if st is None or not st.assumed:
                return
            st.binding_finished = True
            st.deadline = self._now() + self._ttl

    def finish_bindings_bulk(self, pods: List[Pod],
                             keys: Optional[List[str]] = None) -> None:
        """FinishBinding for a batch under one lock; one clock read. `keys`
        lets the caller share already-computed pod keys."""
        deadline = self._now() + self._ttl
        if keys is None:
            keys = [pod.key() for pod in pods]
        with self._lock:
            get = self._pod_states.get
            for key in keys:
                st = get(key)
                if st is None or not st.assumed:
                    continue
                st.binding_finished = True
                st.deadline = deadline

    def forget_pod(self, pod: Pod) -> None:
        key = pod.key()
        with self._lock:
            st = self._pod_states.get(key)
            if st is None:
                return
            if st.pod.node_name != pod.node_name and st.pod.node_name != "":
                # the reference errors on node mismatch (cache.go:161); we
                # tolerate and remove by the cached location
                pass
            if st.assumed:
                self._remove_pod_locked(st.pod)
                del self._pod_states[key]

    def forget_pods_bulk(self, pods: List[Pod]) -> None:
        """ForgetPod for a whole group under ONE lock acquisition — the
        atomic-rollback half of gang scheduling (ISSUE 5): a below-quorum
        or fence-rolled-back gang releases every member's assumed capacity
        in one pass, so no reader interleaves with a half-rolled-back
        gang. Per-pod semantics identical to forget_pod, in order."""
        with self._lock:
            states = self._pod_states
            for pod in pods:
                st = states.get(pod.key())
                if st is not None and st.assumed:
                    self._remove_pod_locked(st.pod)
                    del states[pod.key()]

    def add_pod(self, pod: Pod) -> None:
        """Informer-confirmed pod add (cache.go:214)."""
        key = pod.key()
        with self._lock:
            st = self._pod_states.get(key)
            if st is not None and st.assumed:
                if st.pod.node_name != pod.node_name:
                    # scheduler decision overridden (e.g. another scheduler);
                    # move the pod (cache.go:224-232 updatePod path)
                    self._remove_pod_locked(st.pod)
                    self._add_pod_locked(pod)
                st.pod = pod
                st.assumed = False
                st.deadline = None
            elif st is None:
                self._add_pod_locked(pod)
                self._pod_states[key] = _PodState(pod)

    def update_pod(self, old: Pod, new: Pod) -> None:
        with self._lock:
            st = self._pod_states.get(old.key())
            if st is None:
                self._add_pod_locked(new)
                self._pod_states[new.key()] = _PodState(new)
                return
            self._remove_pod_locked(st.pod)
            self._add_pod_locked(new)
            st.pod = new

    def remove_pod(self, pod: Pod) -> None:
        key = pod.key()
        with self._lock:
            st = self._pod_states.pop(key, None)
            if st is not None:
                self._remove_pod_locked(st.pod)

    def is_assumed(self, pod_key: str) -> bool:
        with self._lock:
            st = self._pod_states.get(pod_key)
            return bool(st and st.assumed)

    def claimed_node(self, pod_key: str) -> Optional[str]:
        """The node this pod currently occupies in cache truth (assumed
        OR confirmed), or None — the bind fence's double-claim probe
        (ISSUE 16): with N independent schedulers racing one cell, a
        commit for a pod some other process already placed must fence
        out as a typed conflict instead of reaching the store."""
        with self._lock:
            st = self._pod_states.get(pod_key)
            if st is None:
                return None
            return st.pod.node_name or None

    def pod_count(self) -> int:
        with self._lock:
            return len(self._pod_states)

    # ----------------------------------------------------------------- nodes

    def add_node(self, node: Node) -> None:
        with self._lock:
            info = self._nodes.get(node.name)
            if info is None:
                info = NodeInfo()
                self._nodes[node.name] = info
            info.set_node(node)

    def update_node(self, node: Node) -> None:
        self.add_node(node)

    def remove_node(self, name: str) -> List[Pod]:
        """RemoveNode (cache.go:328) + the ISSUE 8 liveness audit: ASSUMED
        pods on the removed node are FORGOTTEN (their optimistic capacity
        claim pointed at a node that no longer exists — keeping it would
        leak phantom occupancy until TTL, and the owner must requeue them
        before their bind turns into a ghost) and returned so the owner
        can decide requeue vs orphan. Confirmed pods survive into the
        stub (the informer owns their lifecycle).

        The entry itself becomes a TOMBSTONE (node=None NodeInfo) instead
        of disappearing: the snapshot then marks the row valid=False in
        place — one static-row rewrite — rather than restructuring node
        membership, which costs a FULL re-tensorization + device upload +
        encoding/precompute rebuild per event (at 5k nodes that is
        seconds per kill; 10%/min churn would spend the whole budget
        rebuilding). A respawn under the same name rides the same
        delta path. Podless tombstones are purged in amortized batches
        (purge_tombstones) so permanent departures still reclaim rows."""
        requeue: List[Pod] = []
        with self._lock:
            info = self._nodes.get(name)
            if info is None:
                return requeue
            assumed_keys = set()
            for key, st in self._pod_states.items():
                if st.assumed and st.pod.node_name == name:
                    assumed_keys.add(key)
            for key in assumed_keys:
                st = self._pod_states.pop(key)
                requeue.append(st.pod)
                self._aff_event_locked(st.pod, name, -1)
            survivors = [p for p in info.pods
                         if p.key() not in assumed_keys]
            stub = NodeInfo()
            for p in survivors:
                stub.add_pod(p)
                # the pods' NodeInfo (and its node object) moved —
                # cached topology arrays resolved domains through it;
                # delta 0 = "structure moved", never patchable
                self._aff_event_locked(p, name, 0)
            self._nodes[name] = stub
        return requeue

    def purgeable_tombstones(self) -> int:
        with self._lock:
            return sum(1 for i in self._nodes.values()
                       if i.node is None and not i.pods)

    def purge_tombstones(self) -> int:
        """Drop podless tombstones — the amortized membership compaction.
        The caller must force a full snapshot refresh afterwards (this IS
        the membership restructuring remove_node defers)."""
        with self._lock:
            names = [nm for nm, i in self._nodes.items()
                     if i.node is None and not i.pods]
            for nm in names:
                del self._nodes[nm]
            return len(names)

    # -------------------------------------------------------------- snapshot

    def node_infos(self) -> Dict[str, NodeInfo]:
        """Live references (caller must treat as read-only, or hold no pointer
        across mutations). The tensor snapshot reads generations from these —
        the moral equivalent of UpdateNodeNameToInfoMap (cache.go:79)."""
        with self._lock:
            return dict(self._nodes)

    def node_info(self, name: str) -> Optional[NodeInfo]:
        """One live NodeInfo reference (same read-only contract as
        node_infos) — the fast-lane fence re-validates its single winner
        without copying the whole map (ISSUE 17)."""
        with self._lock:
            return self._nodes.get(name)

    def affinity_pod_count(self) -> int:
        """Resident pods carrying affinity/anti-affinity terms — the
        fast lane falls back to the full wave eval whenever this is
        nonzero (ISSUE 17)."""
        with self._lock:
            return self._aff_pods

    def snapshot_infos(self) -> Dict[str, NodeInfo]:
        with self._lock:
            return {k: v.clone_shallow() for k, v in self._nodes.items()}

    # -------------------------------------------------------------- expiry

    def cleanup_assumed(self) -> List[str]:
        """Expire assumed pods whose bind was never confirmed within TTL
        (cache.go:355 cleanupAssumedPods). Returns expired pod keys."""
        expired = []
        now = self._now()
        with self._lock:
            for key, st in list(self._pod_states.items()):
                if st.assumed and st.binding_finished and st.deadline is not None \
                        and now >= st.deadline:
                    self._remove_pod_locked(st.pod)
                    del self._pod_states[key]
                    expired.append(key)
        return expired

    # -------------------------------------------------------------- internal

    def _add_pod_locked(self, pod: Pod) -> None:
        lockcheck.assert_held(self._lock, "_add_pod_locked")
        info = self._nodes.get(pod.node_name)
        if info is None:
            info = NodeInfo()
            self._nodes[pod.node_name] = info
        info.add_pod(pod)
        self._aff_event_locked(pod, pod.node_name, 1)

    def _remove_pod_locked(self, pod: Pod) -> None:
        lockcheck.assert_held(self._lock, "_remove_pod_locked")
        info = self._nodes.get(pod.node_name)
        if info is not None:
            info.remove_pod(pod)
            self._aff_event_locked(pod, pod.node_name, -1)


class BindLedger:
    """Idempotency ledger for /bind over the wire (ISSUE 9): exactly-once
    replay protection for the at-most-once ambiguity PR 8 solved in-process.

    A frontend whose /bind timed out cannot know whether the bind LANDED
    (response lost) or never ran (request lost). It retries with the SAME
    idempotency key; the ledger makes that retry converge instead of
    double-booking:

      - ``ok``        -> the bind completed; the retry is answered from the
        record with no second assume and no second apiserver write;
      - ``uncertain`` -> the server's own downstream write errored (which
        is itself ambiguous — a bind API timeout may have landed). The
        retry REPLAYS against the RECORDED node, never a fresh choice:
        re-binding the recorded node is idempotent at the store ("already
        assigned to <same node>" heals to success), while a fresh choice
        after a landed write would be the duplicate bind this ledger
        exists to prevent;
      - ``pending``   -> a concurrent duplicate (client retried while the
        original is still in flight): answered retryable-busy, the client
        backs off and re-asks;
      - ``conflict``  -> the fence refused the attempt; nothing landed, so
        a replayed duplicate of THAT attempt gets the same typed answer
        (the client's next attempt uses a fresh key for its fresh choice).

    Bounded LRU over COMPLETED entries (pending/uncertain entries are
    pinned — trimming an uncertain record would reopen the ambiguity
    window); own lock, so ledger reads never contend with the backend's
    evaluation lock."""

    def __init__(self, cap: int = 65536):
        from collections import OrderedDict
        self._cap = cap
        self._lock = lockcheck.make_lock("BindLedger._lock")
        self._entries: "OrderedDict[str, list]" = OrderedDict()
        # entry: [status, node, error] with status in
        # {"pending", "ok", "conflict", "uncertain"}

    def begin(self, key: str, node: str):
        """Open (or re-open) an attempt. Returns (verdict, node, error):
        verdict "fresh" -> proceed with the caller's node; "replay" ->
        proceed with the RETURNED node (a prior uncertain attempt owns the
        choice); "done" -> answer (node, error) without doing anything;
        "pending" -> a twin is in flight, answer retryable-busy."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self._entries[key] = ["pending", node, ""]
                self._trim_locked()
                return "fresh", node, ""
            status = e[0]
            if status == "pending":
                return "pending", e[1], ""
            if status in ("ok", "conflict"):
                self._entries.move_to_end(key)
                return "done", e[1], e[2]
            # uncertain: the retry re-runs the attempt against the
            # recorded node (see class docstring)
            e[0] = "pending"
            return "replay", e[1], e[2]

    def finish(self, key: str, status: str, error: str = "") -> None:
        """Record an attempt's outcome: "ok" | "conflict" | "uncertain"."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self._entries[key] = [status, "", error]
            else:
                e[0] = status
                e[2] = error
            self._trim_locked()

    def abandon(self, key: str) -> None:
        """Drop a PENDING entry whose attempt did nothing (shed before any
        side effect), so a same-key retry starts fresh instead of replaying
        a non-attempt. No-op for completed or uncertain records."""
        with self._lock:
            e = self._entries.get(key)
            if e is not None and e[0] == "pending":
                del self._entries[key]

    def _trim_locked(self) -> None:
        # evict oldest COMPLETED entries only (docstring: pending and
        # uncertain records are pinned). Incremental oldest-first scan —
        # at capacity this runs per bind, and materializing a 65k-key
        # list per commit would put an O(cap) copy on the bind hot path
        lockcheck.assert_held(self._lock, "_trim_locked")
        while len(self._entries) > self._cap:
            for k in self._entries:
                if self._entries[k][0] in ("ok", "conflict"):
                    del self._entries[k]
                    break
            else:
                return  # everything live is pinned

    def stats(self):
        with self._lock:
            out = {"entries": len(self._entries)}
            for st in ("pending", "ok", "conflict", "uncertain"):
                out[st] = sum(1 for e in self._entries.values()
                              if e[0] == st)
            return out
