"""Host-side volume resolution: conflict keys, PD-count filters, zone labels,
PV node affinity.

This is the object→identity layer shared by the exact oracle predicates
(ops/oracle_volumes.py) and the tensorization (state/snapshot.py). The
reference spreads this logic across predicates.go:128-474 (isVolumeConflict,
MaxPDVolumeCountChecker.filterVolumes, VolumeZoneChecker, VolumeNodeChecker)
and pkg/volume/util/util.go:193 (CheckNodeAffinity).

Design note (TPU-first): every volume fact is reduced to an *interned string
key* so the kernels see multi-hot rows over small demand-driven vocabularies —
set intersection becomes an int8 matmul. Keys are exact (no hashing), so
kernel verdicts equal oracle verdicts; see state/snapshot.py docstring.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu.api.types import (
    ALPHA_STORAGE_NODE_AFFINITY_ANNOTATION,
    NodeSelectorTerm,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    SelectorOperator,
    SelectorRequirement,
    Volume,
    VolumeKind,
)

# zone/region labels (kubeletapis.LabelZoneFailureDomain / LabelZoneRegion,
# read by VolumeZoneChecker — predicates.go:420-426)
ZONE_LABEL = "failure-domain.beta.kubernetes.io/zone"
REGION_LABEL = "failure-domain.beta.kubernetes.io/region"

# Max PD volume defaults (algorithmprovider/defaults/defaults.go:33-47 +
# pkg/cloudprovider/providers/aws/aws.go DefaultMaxEBSVolumes=39)
DEFAULT_MAX_EBS_VOLUMES = 39
DEFAULT_MAX_GCE_PD_VOLUMES = 16
DEFAULT_MAX_AZURE_DISK_VOLUMES = 16
KUBE_MAX_PD_VOLS_ENV = "KUBE_MAX_PD_VOLS"

# PD filter kinds, in fixed column order for the [N,3] count tensors
PD_EBS, PD_GCE, PD_AZURE = 0, 1, 2
PD_KINDS = (VolumeKind.AWS_EBS, VolumeKind.GCE_PD, VolumeKind.AZURE_DISK)
PD_PREDICATE_NAMES = ("MaxEBSVolumeCount", "MaxGCEPDVolumeCount",
                      "MaxAzureDiskVolumeCount")


def max_pd_volumes() -> Tuple[int, int, int]:
    """(ebs, gce, azure) limits honoring KUBE_MAX_PD_VOLS
    (defaults.go:233-246 getMaxVols)."""
    raw = os.environ.get(KUBE_MAX_PD_VOLS_ENV, "")
    if raw:
        try:
            v = int(raw)
            if v > 0:
                return v, v, v
        except ValueError:
            pass
    return (DEFAULT_MAX_EBS_VOLUMES, DEFAULT_MAX_GCE_PD_VOLUMES,
            DEFAULT_MAX_AZURE_DISK_VOLUMES)


class VolumeContext:
    """PV/PVC lister mirror (the pvInfo/pvcInfo of
    NewMaxPDVolumeCountPredicate — factory.go wires informer listers).
    `version` bumps on any PV/PVC change so consumers can invalidate
    derived tensors."""

    def __init__(self,
                 pvs: Optional[Dict[str, PersistentVolume]] = None,
                 pvcs: Optional[Dict[Tuple[str, str], PersistentVolumeClaim]] = None):
        self.pvs = pvs if pvs is not None else {}
        self.pvcs = pvcs if pvcs is not None else {}
        self.version = 0

    def pv(self, name: str) -> Optional[PersistentVolume]:
        return self.pvs.get(name)

    def pvc(self, namespace: str, name: str) -> Optional[PersistentVolumeClaim]:
        return self.pvcs.get((namespace, name))


EMPTY_VOLUME_CONTEXT = VolumeContext()


# ---------------------------------------------------------------------------
# NoDiskConflict keys
# ---------------------------------------------------------------------------

# conflict "hardness": a HARD request conflicts with ANY existing mount of the
# same key (EBS always — predicates.go:143-147 — plus any read-write mount of
# an RO-capable kind); an RO request conflicts only with a read-write mount.


def conflict_keys(vol: Volume) -> List[Tuple[str, bool]]:
    """-> [(key, read_only)] identity keys for isVolumeConflict
    (predicates.go:128-177). RBD expands to one key per monitor so 'any
    shared monitor + same pool + image' is exact set intersection."""
    kind = VolumeKind(vol.kind)
    if kind == VolumeKind.GCE_PD:
        return [("gce\x00" + vol.volume_id, vol.read_only)]
    if kind == VolumeKind.AWS_EBS:
        # EBS conflicts regardless of read-only: model as never-RO
        return [("ebs\x00" + vol.volume_id, False)]
    if kind == VolumeKind.ISCSI:
        return [("iscsi\x00" + vol.volume_id, vol.read_only)]
    if kind == VolumeKind.RBD:
        return [("rbd\x00" + mon + "\x00" + vol.pool + "\x00" + vol.image,
                 vol.read_only) for mon in vol.monitors]
    return []


def pod_conflict_keys(pod: Pod) -> List[Tuple[str, bool]]:
    out: List[Tuple[str, bool]] = []
    for v in pod.volumes:
        out.extend(conflict_keys(v))
    return out


# ---------------------------------------------------------------------------
# MaxPDVolumeCount filters
# ---------------------------------------------------------------------------


def pd_filter_ids(pod: Pod, ctx: VolumeContext) -> List[Tuple[int, str]]:
    """-> [(pd_kind_index, unique_id)] applying the EBS/GCEPD/AzureDisk
    VolumeFilters with PVC→PV resolution (predicates.go:230-283
    filterVolumes). A missing/unbound PVC or missing PV counts as a unique
    relevant volume (the reference generates a random id; we use a
    deterministic per-(pod,volume) id which dedupes identically within one
    pod — strictly no less conservative)."""
    out: List[Tuple[int, str]] = []
    for i, vol in enumerate(pod.volumes):
        kind = VolumeKind(vol.kind)
        if kind in PD_KINDS:
            out.append((PD_KINDS.index(kind), vol.volume_id))
        elif kind == VolumeKind.PVC:
            claim = vol.volume_id
            if not claim:
                continue  # reference errors; treat as irrelevant
            pvc = ctx.pvc(pod.namespace, claim)
            if pvc is None or not pvc.volume_name:
                # missing or unbound PVC: counts toward EVERY filter's limit
                # in the reference (each predicate's filterVolumes adds it)
                for k in range(len(PD_KINDS)):
                    out.append((k, "\x00missing\x00%s\x00%d" % (pod.uid, i)))
                continue
            pv = ctx.pv(pvc.volume_name)
            if pv is None:
                for k in range(len(PD_KINDS)):
                    out.append((k, "\x00missingpv\x00%s\x00%d" % (pod.uid, i)))
                continue
            pv_kind = VolumeKind(pv.source.kind)
            if pv_kind in PD_KINDS:
                out.append((PD_KINDS.index(pv_kind), pv.source.volume_id))
    return out


def pd_id_sets(pod: Pod, ctx: VolumeContext) -> List[set]:
    """[(set of unique ids)] per PD kind."""
    sets: List[set] = [set() for _ in PD_KINDS]
    for k, vid in pd_filter_ids(pod, ctx):
        sets[k].add(vid)
    return sets


# ---------------------------------------------------------------------------
# VolumeZone
# ---------------------------------------------------------------------------


class UnresolvedVolume(Exception):
    """PVC/PV lookup failed where the reference returns a scheduling error
    (predicates.go:434-458) — the pod cannot be scheduled this round."""


def zone_constraints(pod: Pod, ctx: VolumeContext) -> List[Tuple[str, str]]:
    """Required (zone-label-key, value) pairs from the pod's bound PVs
    (predicates.go:404-474 VolumeZoneChecker.predicate). Raises
    UnresolvedVolume on missing/unbound PVC or missing PV."""
    out: List[Tuple[str, str]] = []
    for vol in pod.volumes:
        if VolumeKind(vol.kind) != VolumeKind.PVC:
            continue
        claim = vol.volume_id
        if not claim:
            raise UnresolvedVolume("PersistentVolumeClaim had no name")
        pvc = ctx.pvc(pod.namespace, claim)
        if pvc is None:
            raise UnresolvedVolume(f"PersistentVolumeClaim not found: {claim}")
        if not pvc.volume_name:
            raise UnresolvedVolume(f"PersistentVolumeClaim not bound: {claim}")
        pv = ctx.pv(pvc.volume_name)
        if pv is None:
            raise UnresolvedVolume(
                f"PersistentVolume not found: {pvc.volume_name}")
        for k, v in pv.labels.items():
            if k in (ZONE_LABEL, REGION_LABEL):
                out.append((k, v))
    return out


def node_zone_check(node_labels: Dict[str, str],
                    constraints: Sequence[Tuple[str, str]]) -> bool:
    """predicates.go:415-470: a node with no zone/region labels passes; else
    each PV zone label must equal the node's value for that key (missing key
    compares as \"\")."""
    node_zone = {k: v for k, v in node_labels.items()
                 if k in (ZONE_LABEL, REGION_LABEL)}
    if not node_zone:
        return True
    for k, v in constraints:
        if node_zone.get(k, "") != v:
            return False
    return True


# ---------------------------------------------------------------------------
# VolumeNode (PersistentLocalVolumes alpha)
# ---------------------------------------------------------------------------


def parse_pv_node_affinity(pv: PersistentVolume) -> Optional[List[NodeSelectorTerm]]:
    """Node-selector terms from the PV: explicit field, else the alpha
    annotation (helpers.go:418 GetStorageNodeAffinityFromAnnotation). Terms
    are ANDed at check time (util.go:202-214)."""
    if pv.node_affinity_terms is not None:
        return pv.node_affinity_terms
    raw = pv.annotations.get(ALPHA_STORAGE_NODE_AFFINITY_ANNOTATION, "")
    if not raw:
        return None
    try:
        obj = json.loads(raw)
    except ValueError as e:
        raise UnresolvedVolume(f"bad node-affinity annotation: {e}") from None
    req = (obj or {}).get("requiredDuringSchedulingIgnoredDuringExecution")
    if not req:
        return None
    terms = []
    for t in req.get("nodeSelectorTerms", []):
        exprs = [
            SelectorRequirement(e["key"], SelectorOperator(e["operator"]),
                                list(e.get("values", [])))
            for e in t.get("matchExpressions", [])
        ]
        terms.append(NodeSelectorTerm(exprs))
    return terms


def pv_affinity_requirements(pod: Pod, ctx: VolumeContext
                             ) -> List[SelectorRequirement]:
    """Flattened AND of every bound PV's node-affinity requirements
    (VolumeNodeChecker.predicate, predicates.go:1354-1411 + util.go:193).
    Raises UnresolvedVolume like the reference's error returns."""
    reqs: List[SelectorRequirement] = []
    for vol in pod.volumes:
        if VolumeKind(vol.kind) != VolumeKind.PVC:
            continue
        claim = vol.volume_id
        if not claim:
            raise UnresolvedVolume("PersistentVolumeClaim had no name")
        pvc = ctx.pvc(pod.namespace, claim)
        if pvc is None:
            raise UnresolvedVolume(f"PersistentVolumeClaim not found: {claim}")
        if not pvc.volume_name:
            raise UnresolvedVolume(f"PersistentVolumeClaim not bound: {claim}")
        pv = ctx.pv(pvc.volume_name)
        if pv is None:
            raise UnresolvedVolume(
                f"PersistentVolume not found: {pvc.volume_name}")
        terms = parse_pv_node_affinity(pv)
        if terms:
            for t in terms:
                reqs.extend(t.match_expressions)
    return reqs
