"""Tensorization: NodeInfo cache -> dense device arrays; pending pods -> batch tensors.

This is the layer that makes the scheduler TPU-native. The reference evaluates
predicates/priorities object-by-object with a 16-worker fan-out
(plugin/pkg/scheduler/core/generic_scheduler.go:204,352); here the entire
cluster becomes a handful of dense arrays so the whole pending queue is one
fused pod x node kernel (kubernetes_tpu/ops/).

Encoding strategy ("everything is a masked matmul"):

- Label (key,value) pairs, taints, extended-resource names are interned into
  host-side vocabularies with stable indices; nodes/pods carry multi-hot rows
  over the vocab axis. Because the vocabularies are built from the actual
  cluster objects, the encoding is EXACT — set operations (selector matching,
  toleration coverage) lower to int8 matmuls + integer compares with no false
  positives/negatives (vs. the hashing scheme sketched in SURVEY.md §7(e);
  exact host-side verification is therefore only needed for features the
  kernels don't model yet, flagged via PodBatch.needs_host_check).

- Resource quantities are int32. CPU stays millicores; memory/storage are
  quantized to KiB (allocatable rounded DOWN, requests rounded UP — so
  quantization can only make placement more conservative, never overcommit).
  Score arithmetic needs (capacity * 10) to fit in int31 -> supports nodes up
  to ~200 GiB memory at KiB granularity; raise mem_shift for bigger nodes.
  All reference test fixtures use Mi-multiples, where KiB is lossless.

- Host ports become a packed 65536-bit bitmap per node (uint32 x 2048 words);
  pod wanted-ports are index lists with -1 sentinel. Conflict check is a
  gather, not a matmul — exact over the full port space.

- Incremental refresh mirrors the generation-counter diffing of
  UpdateNodeNameToInfoMap (reference: schedulercache/cache.go:79): each node
  row is rewritten only when its NodeInfo.generation moved; vocab growth or
  node-set membership change triggers a (rare) full rebuild + recompile-safe
  padded widening.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kubernetes_tpu.api.types import (
    MAX_PRIORITY,
    Affinity,
    ConditionStatus,
    Node,
    NodeSelectorTerm,
    Pod,
    SelectorOperator,
    SelectorRequirement,
    Taint,
    TaintEffect,
    Toleration,
)
from kubernetes_tpu.state.node_info import NodeInfo
from kubernetes_tpu.state import volumes as volmod

# Base resource columns (extended resources follow, via vocab)
R_CPU, R_MEM, R_GPU, R_SCRATCH, R_OVERLAY = 0, 1, 2, 3, 4
NUM_BASE_RESOURCES = 5

PORT_SPACE = 65536
PORT_WORDS = PORT_SPACE // 32


def _pad(n: int, to: int = 8) -> int:
    """Pad a vocab axis so occasional growth doesn't force a recompile."""
    return max(to, ((n + to - 1) // to) * to)


from kubernetes_tpu.api.annotations import (  # shared with ops.oracle_ext
    AVOID_PODS_ANNOTATION,
    parse_avoid_annotation as _parse_avoid_annotation,
)


class Vocab:
    """Interning table with stable indices and a by-key reverse map for
    expanding Exists/DoesNotExist/Gt/Lt requirements into pair sets."""

    def __init__(self):
        self._index: Dict[Tuple[str, str], int] = {}
        self._items: List[Tuple[str, str]] = []
        self.by_key: Dict[str, List[int]] = {}

    def add(self, key: str, value: str = "") -> int:
        item = (key, value)
        idx = self._index.get(item)
        if idx is None:
            idx = len(self._items)
            self._index[item] = idx
            self._items.append(item)
            self.by_key.setdefault(key, []).append(idx)
        return idx

    def get(self, key: str, value: str = "") -> int:
        return self._index.get((key, value), -1)

    def __len__(self) -> int:
        return len(self._items)

    def items(self) -> List[Tuple[str, str]]:
        return self._items


class ClusterSnapshot:
    """Dense mirror of the SchedulerCache's node map.

    Arrays (N = padded node count):
      alloc        int32 [N, R]   allocatable (R = 5 base + extended vocab)
      requested    int32 [N, R]   sum of bound+assumed pod requests
      nonzero      int32 [N, 2]   nonzero-request cpu/mem sums (priorities)
      pod_count    int32 [N]
      allowed_pods int32 [N]
      schedulable  bool  [N]      CheckNodeConditionPredicate verdict
      mem_pressure bool  [N]
      disk_pressure bool [N]
      labels       int8  [N, L]   multi-hot over label-pair vocab
      taints_sched int8  [N, T]   NoSchedule|NoExecute taints, taint vocab
      taints_pref  int8  [N, T]   PreferNoSchedule taints (priority only)
      port_bitmap  uint32 [N, 2048]
      valid        bool  [N]      real node (False for padding rows)

    The label-pair vocabulary is DEMAND-driven: only pairs some pod selector
    references get columns (interned via ensure_* during PodBatch compile).
    Node-unique labels like kubernetes.io/hostname therefore cost nothing
    unless selected on — without this, L scales with cluster size and the
    selector tensors dominate host->HBM transfer. Exactness is preserved:
    a pair no selector mentions can never affect a match verdict.

    `dirty` names the arrays whose host copy changed since the consumer
    (engine) last uploaded — pod add/remove touches only requested/nonzero/
    pod_count (+port_bitmap when the pod has host ports), so steady-state
    rounds re-upload ~KBs, not the full snapshot.
    """

    DYNAMIC = ("requested", "nonzero", "pod_count")
    # priority-band axis width (ISSUE 14): distinct pod PRIORITY values
    # intern into band columns first-seen; a cluster with more distinct
    # priorities than this sets prio_band_overflow and the wave-path
    # victim scan falls back to the host pre-filter (the same
    # over-width -> exact-path routing every other vocab uses)
    PRIO_BANDS = 16
    BAND_UNUSED_PRIO = 2 ** 62
    STATIC = ("alloc", "allowed_pods", "schedulable", "mem_pressure",
              "disk_pressure", "labels", "taints_sched", "taints_pref", "valid",
              "avoid", "image_sizes", "has_zone")

    def __init__(self, mem_shift: int = 10, node_pad: int = 8):
        self.mem_shift = mem_shift
        self.node_pad = node_pad
        self.label_vocab = Vocab()
        self.taint_vocab = Vocab()  # key=(taint key) value=(value|effect)
        self.ext_vocab = Vocab()  # extended resource names
        self.node_names: List[str] = []
        self.node_index: Dict[str, int] = {}
        self._generations: Dict[str, Tuple[int, int, int]] = {}
        self._shape_sig: Optional[Tuple[int, ...]] = None
        self.version = 0  # bumped on any array change (device cache key)
        # encoding generation: bumped only when something that affects POD
        # ENCODING changes — vocab widths/content (finalize_*) or node
        # membership/order (_allocate; PodFitsHost rows store node indices).
        # Capacity deltas (a bind) bump `version` but NOT `vocab_gen`, so
        # the extender's per-class encodings (PodBatch arrays) stay valid
        # across a scheduleOne stream of binds.
        self.vocab_gen = 0
        # label-CONTENT generation: bumped whenever any node's label ROW
        # changes value (relabel to already-interned columns rides the
        # delta refresh without touching vocab_gen, but anything that
        # materialized label content — the wave encoding's key_node /
        # static_forbid / labels_aff topology views — is stale then)
        self.labels_gen = 0
        # Protean patch log for label-row churn (ISSUE 8): every
        # labels_gen bump appends (gen_after, row), so a consumer whose
        # baked topology views fell one relabel behind can re-derive the
        # touched ROWS instead of rebuilding wholesale. Bounded ring;
        # a consumer further behind than the ring rebuilds.
        self._labels_log: List[Tuple[int, int]] = []
        self.dirty: set = set()
        # ROW-granular dirt for the DYNAMIC arrays (requested/nonzero/
        # pod_count), consumed by the sharded device sync (ISSUE 12): a
        # mesh-resident engine re-uploads only the SHARDS owning touched
        # rows instead of the whole [N, R] array per wave. None = unknown
        # (full upload required); the consumer arms tracking by assigning
        # a fresh set after each full sync. Writers that know their rows
        # call _note_rows; writers that rewrite wholesale call
        # _note_rows(None).
        self.dirty_rows = None
        # priority-band vocab (ISSUE 14): pod priority value -> band
        # column. Band growth never bumps vocab_gen — the bands are
        # preemption-only state no pod encoding reads — and the band
        # arrays live beside the dynamic rows (maintained by the same
        # writers, folded by apply_assume_delta), so the victim scan
        # reads per-node evictable aggregates without any pod walk.
        self.prio_bands: Dict[int, int] = {}
        self.band_prio_host = np.full(self.PRIO_BANDS,
                                      self.BAND_UNUSED_PRIO, dtype=np.int64)
        self.prio_band_overflow = False
        self._label_index: Dict[str, set] = {}  # key -> values across nodes
        self._row_labels: List[Dict[str, str]] = []  # per-row node label maps
        self._labels_width = _pad(0)
        self._vocab_dirty = False
        # NodePreferAvoidPods: vocab of avoided (kind, uid) controller sigs
        self.avoid_vocab = Vocab()
        # ImageLocality: demand-driven vocab of image names pods reference;
        # node rows rebuilt on growth like the label matrix
        self.image_vocab = Vocab()
        self._row_images: List[list] = []
        self._images_width = _pad(0, 4)
        self._image_vocab_dirty = False
        # Volume predicates: demand-driven vocabs of conflict keys
        # (NoDiskConflict) and PD ids (MaxPDVolumeCount). Presence matrices
        # rebuilt on vocab growth like images; pd_counts (unique filtered
        # volumes per node per kind) is vocab-independent host math.
        self.conflict_vocab = Vocab()
        self.pd_vocab = Vocab()  # key = "<kind_idx>\x00<id>"
        self.pd_max = np.array(volmod.max_pd_volumes(), dtype=np.int32)
        self.volume_ctx: volmod.VolumeContext = volmod.EMPTY_VOLUME_CONTEXT
        self._vol_ctx_ver = -1
        self._row_vol_conflicts: List[list] = []  # [(key, read_only)]
        self._row_vol_pds: List[list] = []  # [(kind_idx, id)]
        self._conflict_width = _pad(0, 4)
        self._pd_width = _pad(0, 4)
        self._vol_vocab_dirty = False
        # fast-lane headroom view cache (ISSUE 17): (weights, ok) derived
        # from the resident arrays, keyed on `version` — a fast pop with
        # no intervening snapshot change reuses it for free
        self._headroom = None
        self._headroom_version = -1
        # arrays created on first refresh
        self.alloc: np.ndarray
        self.requested: np.ndarray
        self.nonzero: np.ndarray
        self.pod_count: np.ndarray
        self.allowed_pods: np.ndarray
        self.schedulable: np.ndarray
        self.mem_pressure: np.ndarray
        self.disk_pressure: np.ndarray
        self.labels: np.ndarray
        self.taints_sched: np.ndarray
        self.taints_pref: np.ndarray
        self.port_bitmap: np.ndarray

    # ------------------------------------------------------------------ api

    @property
    def num_resources(self) -> int:
        return NUM_BASE_RESOURCES + _pad(len(self.ext_vocab), 4)

    def quant_mem(self, v: int, up: bool) -> int:
        if up:
            return -((-v) >> self.mem_shift)
        return v >> self.mem_shift

    def resource_row(self, *, milli_cpu: int, memory: int, gpu: int, scratch: int,
                     overlay: int, extended: Dict[str, int], up: bool,
                     width: int, unknown: Optional[List[str]] = None) -> np.ndarray:
        """Encode one resource vector. The ext vocab is CLOSED here — refresh()
        interns every name visible in node allocatable/requested before the
        arrays are shaped. A name still unknown (only possible for a pending
        pod requesting a resource no node advertises) is appended to `unknown`
        so the caller can mark the pod impossible-to-place instead of
        overflowing the padded width."""
        row = np.zeros(width, dtype=np.int32)
        row[R_CPU] = milli_cpu
        row[R_MEM] = self.quant_mem(memory, up)
        row[R_GPU] = gpu
        row[R_SCRATCH] = self.quant_mem(scratch, up)
        row[R_OVERLAY] = self.quant_mem(overlay, up)
        for name, q in extended.items():
            idx = self.ext_vocab.get(name, "")
            if idx < 0:
                if unknown is None:
                    raise KeyError(
                        f"extended resource {name!r} missing from vocab — "
                        "refresh() must intern node-side names first")
                unknown.append(name)
                continue
            row[NUM_BASE_RESOURCES + idx] = q
        return row

    def headroom_view(self):
        """(weights float64 [N], ok bool [N]) for the fast lane's
        weighted power-of-k sampling (ISSUE 17): weight = spare CPU + 1
        on rows that could plausibly take a pod (live + schedulable +
        pod-count headroom), 0 elsewhere. Derived from the RESIDENT host
        arrays only — no refresh, no device read — and cached on
        `version` so back-to-back fast pops between snapshot changes pay
        one subtract. Approximate by design: the sampled eval re-checks
        everything exactly, the fence re-validates against live truth."""
        if self._headroom_version == self.version and \
                self._headroom is not None:
            return self._headroom
        spare = np.clip(self.alloc[:, R_CPU] - self.requested[:, R_CPU],
                        0, None).astype(np.float64)
        ok = (self.schedulable & self.valid
              & (self.pod_count < self.allowed_pods))
        weights = np.where(ok, spare + 1.0, 0.0)
        self._headroom = (weights, ok)
        self._headroom_version = self.version
        return self._headroom

    def ensure_label_pair(self, key: str, value: str) -> int:
        """Intern a selector-referenced pair; marks the label matrix stale
        when the vocab grows."""
        before = len(self.label_vocab)
        idx = self.label_vocab.add(key, value)
        if len(self.label_vocab) != before:
            self._vocab_dirty = True
        return idx

    def node_values_for_key(self, key: str):
        """Values present for `key` across current nodes (for Exists/Gt/Lt/
        DoesNotExist expansion)."""
        return self._label_index.get(key, ())

    def ensure_image(self, name: str) -> int:
        before = len(self.image_vocab)
        idx = self.image_vocab.add(name, "")
        if len(self.image_vocab) != before:
            self._image_vocab_dirty = True
        return idx

    def finalize_images(self) -> int:
        """Rebuild [N, I] image-size matrix (KiB, clamped to int32) if the
        image vocab grew. Mirrors finalize_labels."""
        want = _pad(len(self.image_vocab), 4)
        if self._image_vocab_dirty or want != self._images_width:
            self._images_width = want
            n = self.alloc.shape[0] if self._shape_sig else 0
            self.image_sizes = np.zeros((n, want), dtype=np.int32)
            for i, images in enumerate(self._row_images):
                self._write_image_row(i, images)
            self._image_vocab_dirty = False
            self.dirty.add("image_sizes")
            self.version += 1
            self.vocab_gen += 1
        return self._images_width

    def ensure_conflict_key(self, key: str) -> int:
        before = len(self.conflict_vocab)
        idx = self.conflict_vocab.add(key, "")
        if len(self.conflict_vocab) != before:
            self._vol_vocab_dirty = True
        return idx

    def ensure_pd_id(self, kind_idx: int, vid: str) -> int:
        before = len(self.pd_vocab)
        idx = self.pd_vocab.add(str(kind_idx) + "\x00" + vid, "")
        if len(self.pd_vocab) != before:
            self._vol_vocab_dirty = True
        return idx

    def finalize_volumes(self) -> Tuple[int, int]:
        """Rebuild the node-side volume presence matrices if either volume
        vocab grew (PodBatch compile interns pending pods' keys). Returns
        (conflict_width, pd_width)."""
        want_c = _pad(len(self.conflict_vocab), 4)
        want_p = _pad(len(self.pd_vocab), 4)
        if (self._vol_vocab_dirty or want_c != self._conflict_width
                or want_p != self._pd_width):
            self._conflict_width = want_c
            self._pd_width = want_p
            n = self.alloc.shape[0] if self._shape_sig else 0
            self.vol_present = np.zeros((n, want_c), dtype=np.int8)
            self.vol_rw = np.zeros((n, want_c), dtype=np.int8)
            self.pd_present = np.zeros((n, want_p), dtype=np.int8)
            for i in range(len(self.node_names)):
                self._write_volume_presence_row(i)
            # [3, Vpd] kind mask over pd vocab columns
            self.pd_kind = np.zeros((3, want_p), dtype=np.int8)
            for col, (key, _) in enumerate(self.pd_vocab.items()):
                self.pd_kind[int(key.split("\x00", 1)[0]), col] = 1
            self._vol_vocab_dirty = False
            self.dirty.update(("vol_present", "vol_rw", "pd_present",
                               "pd_kind"))
            self.version += 1
            self.vocab_gen += 1
        return self._conflict_width, self._pd_width

    def finalize_labels(self) -> int:
        """Rebuild the [N, L] label matrix if the vocab grew (called by
        PodBatch after selector compilation). Returns the padded width L."""
        want = _pad(len(self.label_vocab))
        if self._vocab_dirty or want != self._labels_width:
            self._labels_width = want
            n = self.alloc.shape[0] if self._shape_sig else 0
            self.labels = np.zeros((n, want), dtype=np.int8)
            # batch scatter through the native encoder (C++ hostops with
            # numpy fallback) instead of a per-row rewrite loop — this is
            # the full-matrix rebuild every vocab growth pays
            self._scatter_labels(len(self._row_labels))
            self._vocab_dirty = False
            self.dirty.add("labels")
            self.version += 1
            self.vocab_gen += 1
            if self._shape_sig is not None:
                # keep the shape signature in sync so the next refresh()
                # doesn't mistake the widened label axis for a rebuild
                sig = list(self._shape_sig)
                sig[1] = want
                self._shape_sig = tuple(sig)
        return self._labels_width

    def refresh(self, infos: Dict[str, NodeInfo],
                volume_ctx: Optional[volmod.VolumeContext] = None,
                changed_hint: Optional[Sequence[str]] = None) -> bool:
        """Sync arrays with the cache. Returns True on full rebuild (shape or
        membership change), False for in-place delta. A PV/PVC change
        (volume_ctx.version moved) re-resolves every node's PD rows — the
        ecache-style invalidation of factory.go:261-601 for PV/PVC events.

        changed_hint: the caller ASSERTS node membership is unchanged and
        only the named nodes may have moved (the extender's per-bind path,
        where walking all N generation counters per request would dominate
        a warm [1,N] evaluation). Verification is PARTIAL: spec/ports/
        identity changes and unseen extended resources on the HINTED nodes,
        plus a size change of the node set, fall back to the full scan —
        but changes to non-hinted nodes (including an equal-size node swap)
        are trusted, not checked; a caller that cannot uphold the assertion
        must not pass a hint. TPUExtenderBackend upholds it by owning its
        cache exclusively and escalating every sync to a full refresh."""
        if volume_ctx is not None:
            self.volume_ctx = volume_ctx
        vol_ctx_moved = self._vol_ctx_ver != self.volume_ctx.version
        self._vol_ctx_ver = self.volume_ctx.version
        from kubernetes_tpu.utils.trace import COUNTERS
        if changed_hint is not None and not vol_ctx_moved \
                and self._shape_sig is not None \
                and len(infos) == len(self.node_names) \
                and self._refresh_hinted(infos, changed_hint):
            COUNTERS.inc("snapshot.refresh_hinted")
            return False
        # node-driven vocabs (taints, extended resources, avoid signatures) —
        # interned before shaping, re-scanned only for changed node specs.
        # The skip-cache keys on (spec_generation, node object identity): a
        # node deleted and re-added under the same name restarts its counters,
        # so generation equality alone would skip interning its new spec.
        if not hasattr(self, "_interned_spec"):
            self._interned_spec = {}
        for nm in list(self._interned_spec):
            if nm not in infos:
                del self._interned_spec[nm]
        for nm, info in infos.items():
            node = info.node
            seen = self._interned_spec.get(nm)
            if node is not None and (seen is None or seen[0] != info.spec_generation
                                     or seen[1] is not node):
                self._interned_spec[nm] = (info.spec_generation, node)
                for t in node.taints:
                    eff = t.effect.value if isinstance(t.effect, TaintEffect) else t.effect
                    self.taint_vocab.add(t.key, t.value + "\x00" + str(eff))
                for name in node.allocatable.extended:
                    self.ext_vocab.add(name, "")
                for kind, uid in _parse_avoid_annotation(node.annotations):
                    self.avoid_vocab.add(kind, uid)
            if info.requested.extended:
                # bound/assumed pods may request ext resources their node
                # doesn't advertise; intern so _write_dynamic_row can't overflow
                for name in info.requested.extended:
                    self.ext_vocab.add(name, "")

        names = sorted(infos.keys())
        n_pad = _pad(len(names), self.node_pad)
        sig = (n_pad, self._labels_width, _pad(len(self.taint_vocab)),
               self.num_resources, _pad(len(self.avoid_vocab), 4))
        rebuild = sig != self._shape_sig or names != self.node_names
        COUNTERS.inc("snapshot.refresh_rebuild" if rebuild
                     else "snapshot.refresh_scan")
        if rebuild:
            self._allocate(names, sig)
            self._label_index = {}
            self._row_labels = [{} for _ in range(n_pad)]
            changed = names
        else:
            # a NodeInfo replaced under the same name (node removed+re-added)
            # restarts its counters — identity is part of the staleness key
            changed = []
            for nm in names:
                prev = self._generations.get(nm)
                info = infos[nm]
                if (prev is None or prev[0] != info.generation
                        or prev[3] is not info or vol_ctx_moved):
                    changed.append(nm)
        label_index_stale = rebuild
        if rebuild:
            # full build: one vectorized pass over every node instead of
            # 3 per-row writers x N (the dominant host cost of a cold
            # 5k-node snapshot)
            self._write_rows_bulk(names, infos)
        else:
            dyn_only = []
            for nm in changed:
                i = self.node_index[nm]
                info = infos[nm]
                prev = self._generations.get(nm, (-1, -1, -1, None))
                fresh = prev[3] is not info
                if not fresh and info.spec_generation == prev[1] \
                        and info.ports_generation == prev[2]:
                    # pure capacity delta: vectorized batch write below
                    dyn_only.append((i, nm, info))
                    continue
                self._write_dynamic_row(i, info)
                if fresh or info.spec_generation != prev[1]:
                    self._write_static_row(i, info)
                    label_index_stale = True
                if fresh or info.ports_generation != prev[2]:
                    self._write_ports_row(i, info)
                self._generations[nm] = (info.generation,
                                         info.spec_generation,
                                         info.ports_generation, info)
            if dyn_only:
                self._write_dynamic_rows_bulk(dyn_only)
        if label_index_stale:
            self._rebuild_label_index(infos, names)
        if changed or rebuild:
            self.version += 1
        return rebuild

    def _refresh_hinted(self, infos: Dict[str, NodeInfo],
                        changed_hint: Sequence[str]) -> bool:
        """Targeted dynamic-row delta for `changed_hint`. Returns True when
        the hint fully covered the update (pure capacity deltas on known
        nodes); False to make the caller run the full generation scan."""
        updates = []
        gens = self._generations
        index = self.node_index
        for nm in changed_hint:
            info = infos.get(nm)
            i = index.get(nm, -1)
            if info is None or i < 0:
                return False  # membership drift — full scan
            prev = gens.get(nm)
            if prev is None or prev[3] is not info \
                    or prev[1] != info.spec_generation \
                    or prev[2] != info.ports_generation:
                return False  # spec/ports/identity moved — needs interning
            if info.requested.extended \
                    and any(self.ext_vocab.get(name, "") < 0
                            for name in info.requested.extended):
                return False  # unseen extended resource — needs interning
            if prev[0] != info.generation:
                updates.append((i, nm, info))
        if updates:
            self._write_dynamic_rows_bulk(updates)
            self.version += 1
        return True

    # ------------------------------------------------- priority bands

    def band_of(self, prio: int, intern: bool = True) -> int:
        """Band column of a pod priority value; interns on first sight.
        Returns -1 (and sets prio_band_overflow when interning) once the
        band axis is full — the caller's cue to take the exact host
        pre-filter instead of the device victim scan."""
        b = self.prio_bands.get(prio)
        if b is not None:
            return b
        if not intern:
            return -1
        if len(self.prio_bands) >= self.PRIO_BANDS:
            self.prio_band_overflow = True
            return -1
        b = len(self.prio_bands)
        self.prio_bands[prio] = b
        self.band_prio_host[b] = prio
        return b

    def _write_band_row(self, i: int, info: NodeInfo) -> None:
        """Recompute one node's band columns from the NodeInfo's
        incremental per-priority aggregate (O(distinct priorities on the
        node), no pod walk)."""
        self.band_cpu[i] = 0
        self.band_mem[i] = 0
        self.band_count[i] = 0
        for prio, u in info.prio_usage.items():
            b = self.band_of(prio)
            if b < 0:
                continue  # overflow: the scan is gated off; best-effort
            self.band_cpu[i, b] = u[0]
            self.band_mem[i, b] = u[1]
            self.band_count[i, b] = u[2]

    def band_bound_counts(self) -> Dict[int, int]:
        """Cluster-wide pod count per priority value (assumed included) —
        the disruption budget's per-band floor reads this."""
        return {prio: int(self.band_count[:, b].sum())
                for prio, b in self.prio_bands.items()}

    def apply_assume_delta(self, rows: np.ndarray, delta: np.ndarray,
                           gen_items, prio_rows=None) -> None:
        """Fold a wave of assumes into the dynamic arrays WITHOUT touching
        the NodeInfos: the caller (the pipelined harvest) knows the exact
        per-placement raw delta (class request + nonzero rows), so the
        mirror applies it to the raw int64 accumulators and re-quantizes
        the touched rows — bit-identical to a full row rewrite from the
        cache, at numpy speed. gen_items = [(name, info)] syncs the
        generation bookkeeping so the next refresh() does not re-walk
        these nodes for a change the mirror already has.

        rows may repeat (one entry per placement); delta is int64 [k, 7]:
        requested cpu/mem/gpu/scratch/overlay, nonzero cpu/mem. Callers
        must route placements with ports/volumes/extended resources through
        the normal dirty-note path instead — those touch more than the
        seven raw columns."""
        from kubernetes_tpu.utils.trace import COUNTERS
        # one count per folded placement: the streaming loop's delta-only
        # invariant reads this against the bound total to PROVE assumes
        # rode the raw-delta path, never a node walk (ISSUE 7)
        COUNTERS.inc("snapshot.assume_delta_rows", len(rows))
        np.add.at(self._raw_dyn, rows, delta)
        np.add.at(self.pod_count, rows, 1)
        if prio_rows is not None and len(rows):
            # fold the placements into the priority-band aggregates too
            # (ISSUE 14): per-row band index from the vocab, interning
            # unseen priorities (band growth invalidates nothing)
            bands = np.fromiter((self.band_of(int(p)) for p in prio_rows),
                                dtype=np.int64, count=len(rows))
            okb = bands >= 0  # overflow rows: scan is gated off anyway
            if okb.any():
                rb, bb = rows[okb], bands[okb]
                np.add.at(self.band_cpu, (rb, bb), delta[okb, 0])
                np.add.at(self.band_mem, (rb, bb), delta[okb, 1])
                np.add.at(self.band_count, (rb, bb), 1)
        touched = np.unique(rows)
        raw = self._raw_dyn[touched]
        shift = self.mem_shift
        requested = self.requested
        requested[touched, R_CPU] = self._i32(raw[:, 0])
        requested[touched, R_MEM] = self._i32(-((-raw[:, 1]) >> shift))
        requested[touched, R_GPU] = self._i32(raw[:, 2])
        requested[touched, R_SCRATCH] = self._i32(-((-raw[:, 3]) >> shift))
        requested[touched, R_OVERLAY] = self._i32(-((-raw[:, 4]) >> shift))
        self.nonzero[touched, 0] = self._i32(raw[:, 5])
        self.nonzero[touched, 1] = self._i32(-((-raw[:, 6]) >> shift))
        gens = self._generations
        for nm, info in gen_items:
            prev = gens.get(nm)
            if prev is not None:  # unseen node: next refresh rewrites it
                gens[nm] = (info.generation, prev[1], prev[2], info)
        self.dirty.update(self.DYNAMIC)
        self._note_rows(touched)
        self.version += 1

    def _write_dynamic_rows_bulk(self, updates) -> None:
        """The work of _write_dynamic_row over a BATCH of (row, name, info)
        triples in vectorized column math — the pipelined drain rewrites
        every assumed-onto node once per wave, so the per-row Python writer
        (resource_row + per-column quantization calls) would dominate the
        round. Rows with extended resources or volume-bearing pods take the
        exact per-row writer; generations update for all."""
        slow = []
        fast = []
        for item in updates:
            info = item[2]
            if info.requested.extended or info.vol_count \
                    or self._row_vol_conflicts[item[0]] \
                    or self._row_vol_pds[item[0]]:
                slow.append(item)
            else:
                fast.append(item)
        if fast:
            n = len(fast)
            idx = np.empty(n, dtype=np.intp)
            base = np.empty((n, 5), dtype=np.int64)
            nz = np.empty((n, 2), dtype=np.int64)
            cnt = np.empty(n, dtype=np.int32)
            for j, (i, _nm, info) in enumerate(fast):
                idx[j] = i
                req = info.requested
                base[j] = (req.milli_cpu, req.memory, req.nvidia_gpu,
                           req.storage_scratch, req.storage_overlay)
                nz[j] = (info.nonzero_cpu, info.nonzero_mem)
                cnt[j] = len(info.pods)
                self._write_band_row(i, info)
            shift = self.mem_shift
            requested = self.requested
            requested[idx, R_CPU] = self._i32(base[:, 0])
            requested[idx, R_MEM] = self._i32(-((-base[:, 1]) >> shift))
            requested[idx, R_GPU] = self._i32(base[:, 2])
            requested[idx, R_SCRATCH] = self._i32(-((-base[:, 3]) >> shift))
            requested[idx, R_OVERLAY] = self._i32(-((-base[:, 4]) >> shift))
            if requested.shape[1] > NUM_BASE_RESOURCES:
                # a node whose last extended-resource pod just left arrives
                # via `slow` (extended keeps zeroed keys); rows here never
                # carry extended requests — clear any stale columns
                requested[idx[:, None],
                          np.arange(NUM_BASE_RESOURCES,
                                    requested.shape[1])] = 0
            self.nonzero[idx, 0] = self._i32(nz[:, 0])
            self.nonzero[idx, 1] = self._i32(-((-nz[:, 1]) >> shift))
            self._raw_dyn[idx, :5] = base
            self._raw_dyn[idx, 5:7] = nz
            self.pod_count[idx] = cnt
            self.dirty.update(self.DYNAMIC)
            self._note_rows(idx)
        for i, _nm, info in slow:
            self._write_dynamic_row(i, info)
        for i, nm, info in updates:
            self._generations[nm] = (info.generation, info.spec_generation,
                                     info.ports_generation, info)

    # ------------------------------------------------------------- internals

    def _allocate(self, names: List[str], sig: Tuple[int, ...]) -> None:
        n, l, t, r = sig[:4]
        self._shape_sig = sig
        # membership/order changed: PodBatch encodings store node indices
        # (PodFitsHost) — every cached encoding keyed on vocab_gen is stale
        self.vocab_gen += 1
        self.node_names = names
        self.node_index = {nm: i for i, nm in enumerate(names)}
        self._generations = {}
        self.alloc = np.zeros((n, r), dtype=np.int32)
        self.requested = np.zeros((n, r), dtype=np.int32)
        self.nonzero = np.zeros((n, 2), dtype=np.int32)
        # raw (unquantized) mirror of the dynamic columns: requested
        # cpu/mem/gpu/scratch/overlay + nonzero cpu/mem — the substrate
        # apply_assume_delta accumulates into so incremental quantization
        # stays bit-identical to a full rewrite (ceil of the TOTAL, not a
        # sum of per-pod ceils)
        self._raw_dyn = np.zeros((n, 7), dtype=np.int64)
        # priority-band aggregates (ISSUE 14): raw int64 per (node, band)
        # sums — quantization happens at upload, so incremental folds and
        # full row rewrites agree bit-exactly
        self.band_cpu = np.zeros((n, self.PRIO_BANDS), dtype=np.int64)
        self.band_mem = np.zeros((n, self.PRIO_BANDS), dtype=np.int64)
        self.band_count = np.zeros((n, self.PRIO_BANDS), dtype=np.int32)
        self.pod_count = np.zeros(n, dtype=np.int32)
        self.allowed_pods = np.zeros(n, dtype=np.int32)
        self.schedulable = np.zeros(n, dtype=bool)
        self.mem_pressure = np.zeros(n, dtype=bool)
        self.disk_pressure = np.zeros(n, dtype=bool)
        self.labels = np.zeros((n, l), dtype=np.int8)
        self.taints_sched = np.zeros((n, t), dtype=np.int8)
        self.taints_pref = np.zeros((n, t), dtype=np.int8)
        self.port_bitmap = np.zeros((n, PORT_WORDS), dtype=np.uint32)
        self._port_words_used = None
        self.valid = np.zeros(n, dtype=bool)
        self.valid[: len(names)] = True
        self.avoid = np.zeros((n, _pad(len(self.avoid_vocab), 4)), dtype=np.int8)
        self.image_sizes = np.zeros((n, self._images_width), dtype=np.int32)
        self._row_images = [[] for _ in range(n)]
        self.has_zone = np.zeros(n, dtype=bool)
        self.vol_present = np.zeros((n, self._conflict_width), dtype=np.int8)
        self.vol_rw = np.zeros((n, self._conflict_width), dtype=np.int8)
        self.pd_present = np.zeros((n, self._pd_width), dtype=np.int8)
        self.pd_counts = np.zeros((n, 3), dtype=np.int32)
        if not hasattr(self, "pd_kind") or self.pd_kind.shape[1] != self._pd_width:
            self.pd_kind = np.zeros((3, self._pd_width), dtype=np.int8)
            for col, (key, _) in enumerate(self.pd_vocab.items()):
                self.pd_kind[int(key.split("\x00", 1)[0]), col] = 1
        self._row_vol_conflicts = [[] for _ in range(n)]
        self._row_vol_pds = [[] for _ in range(n)]
        self.dirty = {"requested", "nonzero", "pod_count", "port_bitmap",
                      "vol_present", "vol_rw", "pd_present", "pd_counts",
                      "pd_kind", *self.STATIC}
        self._note_rows(None)  # fresh arrays: shape moved, full sync

    def _write_rows_bulk(self, names: List[str],
                         infos: Dict[str, NodeInfo]) -> None:
        """Full-rebuild body: the work of _write_dynamic_row +
        _write_static_row + _write_ports_row for every node in one pass —
        scalar columns packed into numpy arrays with vectorized memory
        quantization, sparse features (taints, avoid, images, volumes,
        ports, extended resources) written per row only when present.
        Equivalent to the per-row writers (pinned by test_snapshot's
        bulk-vs-incremental parity test)."""
        n = len(names)
        base = np.zeros((n, 2, 5), dtype=np.int64)  # [node, alloc|req, col]
        nonzero = np.zeros((n, 2), dtype=np.int64)
        for i, nm in enumerate(names):
            info = infos[nm]
            req = info.requested
            base[i, 1] = (req.milli_cpu, req.memory, req.nvidia_gpu,
                          req.storage_scratch, req.storage_overlay)
            nonzero[i] = (info.nonzero_cpu, info.nonzero_mem)
            self.pod_count[i] = len(info.pods)
            self._write_band_row(i, info)
            node = info.node
            if node is None:
                self.schedulable[i] = False
                self.valid[i] = False
            else:
                a = node.allocatable
                base[i, 0] = (a.milli_cpu, a.memory, a.nvidia_gpu,
                              a.storage_scratch, a.storage_overlay)
                self.allowed_pods[i] = node.allowed_pod_number
                self.schedulable[i] = node.is_ready()
                self.mem_pressure[i] = \
                    node.condition("MemoryPressure") == ConditionStatus.TRUE
                self.disk_pressure[i] = \
                    node.condition("DiskPressure") == ConditionStatus.TRUE
                self.valid[i] = True
                self._row_labels[i] = node.labels
                self.has_zone[i] = (volmod.ZONE_LABEL in node.labels
                                    or volmod.REGION_LABEL in node.labels)
                if a.extended:
                    for name, q in a.extended.items():
                        idx = self.ext_vocab.get(name, "")
                        if idx < 0:  # refresh() interns node names first
                            raise KeyError(
                                f"extended resource {name!r} missing from "
                                "vocab — refresh() must intern node-side "
                                "names first")
                        self.alloc[i, NUM_BASE_RESOURCES + idx] = q
                if node.taints:
                    self._write_taint_row(i, node)
                if node.annotations:
                    av = _parse_avoid_annotation(node.annotations)
                    for kind, uid in av:
                        idx = self.avoid_vocab.get(kind, uid)
                        if idx >= 0:
                            self.avoid[i, idx] = 1
                if node.images:
                    self._row_images[i] = node.images
                    self._write_image_row(i, node.images)
            if req.extended:
                for name, q in req.extended.items():
                    idx = self.ext_vocab.get(name, "")
                    if idx < 0:
                        raise KeyError(
                            f"extended resource {name!r} missing from "
                            "vocab — refresh() must intern node-side "
                            "names first")
                    self.requested[i, NUM_BASE_RESOURCES + idx] = q
            if info.used_ports:
                self._write_ports_row(i, info)
            # volume aggregates (same content as _write_dynamic_row)
            if any(p.volumes for p in info.pods):
                conflicts, pd_ids = [], []
                for p in info.pods:
                    if p.volumes:
                        conflicts.extend(volmod.pod_conflict_keys(p))
                        pd_ids.extend(volmod.pd_filter_ids(p, self.volume_ctx))
                self._row_vol_conflicts[i] = conflicts
                self._row_vol_pds[i] = pd_ids
                counts = [set(), set(), set()]
                for k, vid in pd_ids:
                    counts[k].add(vid)
                self.pd_counts[i] = [len(s) for s in counts]
                self._write_volume_presence_row(i)
            self._generations[nm] = (info.generation, info.spec_generation,
                                     info.ports_generation, info)
        # vectorized base columns: alloc rounds DOWN, requested rounds UP
        shift = self.mem_shift
        self.alloc[:n, R_CPU] = self._i32(base[:, 0, 0])
        self.alloc[:n, R_MEM] = self._i32(base[:, 0, 1] >> shift)
        self.alloc[:n, R_GPU] = self._i32(base[:, 0, 2])
        self.alloc[:n, R_SCRATCH] = self._i32(base[:, 0, 3] >> shift)
        self.alloc[:n, R_OVERLAY] = self._i32(base[:, 0, 4] >> shift)
        self.requested[:n, R_CPU] = self._i32(base[:, 1, 0])
        self.requested[:n, R_MEM] = self._i32(-((-base[:, 1, 1]) >> shift))
        self.requested[:n, R_GPU] = self._i32(base[:, 1, 2])
        self.requested[:n, R_SCRATCH] = self._i32(-((-base[:, 1, 3]) >> shift))
        self.requested[:n, R_OVERLAY] = self._i32(-((-base[:, 1, 4]) >> shift))
        self.nonzero[:n, 0] = self._i32(nonzero[:, 0])
        self.nonzero[:n, 1] = self._i32(-((-nonzero[:, 1]) >> shift))
        self._raw_dyn[:n, :5] = base[:, 1]
        self._raw_dyn[:n, 5:7] = nonzero
        self._scatter_labels(n)
        self.dirty.update(self.DYNAMIC)
        self.dirty.update(self.STATIC)
        self._note_rows(None)  # wholesale rewrite — row dirt meaningless

    def _note_rows(self, rows) -> None:
        """Record dynamic-row dirt for the sharded delta sync. rows=None
        means "cannot name the rows" — the next sync uploads wholesale."""
        if rows is None:
            self.dirty_rows = None
        elif self.dirty_rows is not None:
            self.dirty_rows.update(int(r) for r in rows)

    @staticmethod
    def _i32(col: np.ndarray) -> np.ndarray:
        """Checked int64 -> int32 downcast: numpy array assignment WRAPS
        silently where per-row Python-int assignment raised — preserve the
        per-row writers' overflow diagnostic (raise mem_shift)."""
        if col.size and (int(col.max()) > 2 ** 31 - 1
                         or int(col.min()) < -(2 ** 31)):
            raise OverflowError(
                "resource quantity exceeds int32 after quantization — "
                "raise ClusterSnapshot mem_shift")
        return col

    def _scatter_labels(self, n_rows: int) -> None:
        """Label incidence matrix in one batch scatter (native hostops with
        numpy fallback) — shared by finalize_labels and the bulk rebuild."""
        from kubernetes_tpu import native as hostops
        pairs = [(i, idx)
                 for i, lbls in enumerate(self._row_labels[:n_rows])
                 for idx in (self.label_vocab.get(k, v)
                             for k, v in lbls.items())
                 if idx >= 0]
        if pairs:
            hostops.fill_multi_hot(np.asarray(pairs, dtype=np.int64),
                                   self.labels)

    def _write_taint_row(self, i: int, node) -> None:
        for t in node.taints:
            eff = t.effect.value if isinstance(t.effect, TaintEffect) \
                else t.effect
            idx = self.taint_vocab.get(t.key, t.value + "\x00" + str(eff))
            if eff in (TaintEffect.NO_SCHEDULE.value,
                       TaintEffect.NO_EXECUTE.value):
                self.taints_sched[i, idx] = 1
            elif eff == TaintEffect.PREFER_NO_SCHEDULE.value:
                self.taints_pref[i, idx] = 1

    def _write_dynamic_row(self, i: int, info: NodeInfo) -> None:
        self._note_rows((i,))
        r = self.num_resources
        req_ = info.requested
        self._raw_dyn[i] = (req_.milli_cpu, req_.memory, req_.nvidia_gpu,
                            req_.storage_scratch, req_.storage_overlay,
                            info.nonzero_cpu, info.nonzero_mem)
        self.requested[i] = self.resource_row(
            milli_cpu=info.requested.milli_cpu, memory=info.requested.memory,
            gpu=info.requested.nvidia_gpu, scratch=info.requested.storage_scratch,
            overlay=info.requested.storage_overlay,
            extended=info.requested.extended, up=True, width=r)
        self.nonzero[i, 0] = info.nonzero_cpu
        self.nonzero[i, 1] = self.quant_mem(info.nonzero_mem, up=True)
        self.pod_count[i] = len(info.pods)
        self._write_band_row(i, info)
        # volume aggregates over the node's (bound+assumed) pods; volume
        # arrays are dirtied only when the node's volume set actually moved,
        # so volume-less churn keeps steady-state uploads tiny
        conflicts: List[Tuple[str, bool]] = []
        pd_ids: List[Tuple[int, str]] = []
        if any(p.volumes for p in info.pods):
            for p in info.pods:
                if p.volumes:
                    conflicts.extend(volmod.pod_conflict_keys(p))
                    pd_ids.extend(volmod.pd_filter_ids(p, self.volume_ctx))
        vol_changed = (conflicts != self._row_vol_conflicts[i]
                       or pd_ids != self._row_vol_pds[i])
        self._row_vol_conflicts[i] = conflicts
        self._row_vol_pds[i] = pd_ids
        if vol_changed:
            counts = [set(), set(), set()]
            for k, vid in pd_ids:
                counts[k].add(vid)
            self.pd_counts[i] = [len(s) for s in counts]
            self._write_volume_presence_row(i)
            self.dirty.update(("vol_present", "vol_rw", "pd_present",
                               "pd_counts"))
        self.dirty.update(self.DYNAMIC)

    def _assign_row(self, name: str, i: int, value) -> None:
        """Row write with CHANGE DETECTION: dirty only what actually moved
        (ISSUE 8). Under churn most static-row rewrites carry identical
        values (a flap touches only conditions; a respawn restores the
        same spec) — marking every static array dirty per event re-uploads
        megabytes and invalidates the cached wave precompute once per
        fault, which measured as the churn throughput collapse."""
        arr = getattr(self, name)
        if np.array_equal(arr[i], value):
            return
        arr[i] = value
        self.dirty.add(name)

    def _write_static_row(self, i: int, info: NodeInfo) -> None:
        node = info.node
        r = self.num_resources
        if node is None:
            # tombstone (cache.remove_node): the row stays allocated, only
            # the liveness verdicts flip — membership never restructures
            # per churn event
            self._assign_row("schedulable", i, False)
            self._assign_row("valid", i, False)
            return
        self._assign_row("alloc", i, self.resource_row(
            milli_cpu=node.allocatable.milli_cpu, memory=node.allocatable.memory,
            gpu=node.allocatable.nvidia_gpu, scratch=node.allocatable.storage_scratch,
            overlay=node.allocatable.storage_overlay,
            extended=node.allocatable.extended, up=False, width=r))
        self._assign_row("allowed_pods", i, node.allowed_pod_number)
        self._assign_row("schedulable", i, node.is_ready())
        self._assign_row("mem_pressure", i,
                         node.condition("MemoryPressure") == ConditionStatus.TRUE)
        self._assign_row("disk_pressure", i,
                         node.condition("DiskPressure") == ConditionStatus.TRUE)
        self._assign_row("valid", i, True)
        self._row_labels[i] = node.labels
        gen0 = self.labels_gen
        self._write_label_row(i, node.labels)  # content-compared inside
        if self.labels_gen != gen0:
            self.dirty.add("labels")

        old_ts = self.taints_sched[i].copy()
        old_tp = self.taints_pref[i].copy()
        self.taints_sched[i] = 0
        self.taints_pref[i] = 0
        self._write_taint_row(i, node)
        if not np.array_equal(old_ts, self.taints_sched[i]):
            self.dirty.add("taints_sched")
        if not np.array_equal(old_tp, self.taints_pref[i]):
            self.dirty.add("taints_pref")

        av = np.zeros(self.avoid.shape[1], dtype=np.int8)
        for kind, uid in _parse_avoid_annotation(node.annotations):
            idx = self.avoid_vocab.get(kind, uid)
            if idx >= 0:
                av[idx] = 1
        self._assign_row("avoid", i, av)

        self._row_images[i] = node.images
        old_img = self.image_sizes[i].copy() \
            if getattr(self, "image_sizes", None) is not None \
            and self.image_sizes.shape[1] == self._images_width else None
        self._write_image_row(i, node.images)
        if old_img is not None \
                and not np.array_equal(old_img, self.image_sizes[i]):
            self.dirty.add("image_sizes")
        self._assign_row("has_zone", i,
                         any(k in (volmod.ZONE_LABEL, volmod.REGION_LABEL)
                             for k in node.labels))

    # graftlint: gen-ok — per-row helper; every caller (_write_dynamic_row,
    # finalize_images' rebuild loop) owns the dirty note for the batch
    def _write_image_row(self, i: int, images) -> None:
        row = np.zeros(self._images_width, dtype=np.int32)
        for img in images:
            size_kib = min(img.size_bytes >> 10, 2 ** 31 - 1)
            for name in img.names:
                idx = self.image_vocab.get(name, "")
                if idx >= 0:
                    row[idx] = size_kib
        if getattr(self, "image_sizes", None) is not None \
                and self.image_sizes.shape[1] == self._images_width:
            self.image_sizes[i] = row

    # graftlint: gen-ok — per-row helper; callers (_write_dynamic_row,
    # finalize_volumes' rebuild loop) own the dirty note for the batch
    def _write_volume_presence_row(self, i: int) -> None:
        """Multi-hot conflict/PD presence over the demand-driven vocabs; a
        key no pending pod references has no column (and cannot conflict)."""
        if (getattr(self, "vol_present", None) is None
                or self.vol_present.shape[1] != self._conflict_width
                or self.pd_present.shape[1] != self._pd_width
                or i >= len(self._row_vol_conflicts)):
            return
        vc = np.zeros(self._conflict_width, dtype=np.int8)
        vr = np.zeros(self._conflict_width, dtype=np.int8)
        for key, ro in self._row_vol_conflicts[i]:
            idx = self.conflict_vocab.get(key, "")
            if idx >= 0:
                vc[idx] = 1
                if not ro:
                    vr[idx] = 1
        self.vol_present[i] = vc
        self.vol_rw[i] = vr
        pdrow = np.zeros(self._pd_width, dtype=np.int8)
        for k, vid in self._row_vol_pds[i]:
            idx = self.pd_vocab.get(str(k) + "\x00" + vid, "")
            if idx >= 0:
                pdrow[idx] = 1
        self.pd_present[i] = pdrow

    LABELS_LOG_MAX = 1024

    def _write_label_row(self, i: int, labels: Dict[str, str]) -> None:
        lbl = np.zeros(self.labels.shape[1], dtype=np.int8)
        for k, v in labels.items():
            idx = self.label_vocab.get(k, v)
            if idx >= 0:
                lbl[idx] = 1
        if not np.array_equal(self.labels[i], lbl):
            changed = np.nonzero(self.labels[i] != lbl)[0]
            self.labels_gen += 1
            self._labels_log.append((self.labels_gen, i, changed))
            if len(self._labels_log) >= 2 * self.LABELS_LOG_MAX:
                del self._labels_log[:len(self._labels_log)
                                     - self.LABELS_LOG_MAX]
        self.labels[i] = lbl

    def labels_rows_since(self, gen: int) -> Optional[List[tuple]]:
        """(row, changed_columns) entries after `gen` (rows may repeat),
        or None when the bounded ring no longer covers the gap (the
        consumer must rebuild its label-derived views). The changed-column
        sets let a consumer decide PER TERM whether a relabel touched the
        columns its baked domains resolve through — a zone flip must not
        rebuild views whose terms key on hostname columns (ISSUE 8).
        Generations are consecutive integers, so coverage is a length
        check."""
        behind = self.labels_gen - gen
        if behind <= 0:
            return []
        if behind > len(self._labels_log):
            return None
        return [(i, cols) for _g, i, cols in
                self._labels_log[len(self._labels_log) - behind:]]

    def _write_ports_row(self, i: int, info: NodeInfo) -> None:
        if info.used_ports:
            bm = np.zeros(PORT_WORDS, dtype=np.uint32)
            for port in info.used_ports:
                if 0 < port < PORT_SPACE:
                    bm[port // 32] |= np.uint32(1 << (port % 32))
            self.port_bitmap[i] = bm
        else:
            # port-less node (the common case at scale): one memset instead
            # of allocating + copying an 8KB row per node
            self.port_bitmap[i].fill(0)
        self.dirty.add("port_bitmap")
        self._port_words_used = None

    def port_words_used(self) -> int:
        """Highest port-bitmap word in use across all nodes, plus one — the
        engine uploads only [:, :W] of the (otherwise 8KB/node, mostly-zero)
        bitmap. Recomputed lazily when any ports row changed."""
        cached = getattr(self, "_port_words_used", None)
        if cached is None:
            if getattr(self, "port_bitmap", None) is None \
                    or not self.port_bitmap.any():
                cached = 0
            else:
                cached = int(np.nonzero(self.port_bitmap.any(axis=0))[0][-1]) + 1
            self._port_words_used = cached
        return cached

    def domain_node_counts(self) -> np.ndarray:
        """Nodes per interned topology DOMAIN (label-pair column): int64 [L]
        over the current label matrix. The wave engine's affinity
        classification (ops/affinity.py, ISSUE 3) keys on this: a column on
        at most ONE node (the hostname shape) makes per-node conflict
        resolution exactly domain-granular, so required-anti classes over
        singleton-domain keys ride the per-wave mask instead of the strict
        tail. Column indices are PREFIX-STABLE across vocab growth (Vocab
        appends, finalize_labels rebuilds content but never reorders), which
        is also what lets the harvest fence slice live arrays down to an
        older encoding's width."""
        if getattr(self, "labels", None) is None:
            return np.zeros(0, dtype=np.int64)
        return self.labels.sum(axis=0, dtype=np.int64)

    def _rebuild_label_index(self, infos: Dict[str, NodeInfo],
                             names: List[str]) -> None:
        idx: Dict[str, set] = {}
        for nm in names:
            node = infos[nm].node
            if node is None:
                continue
            for k, v in node.labels.items():
                idx.setdefault(k, set()).add(v)
        self._label_index = idx


# ---------------------------------------------------------------------------
# Pod batch tensorization
# ---------------------------------------------------------------------------

MAX_PORTS_PER_POD = 8


def compile_requirements(match_expressions, snap: ClusterSnapshot):
    """Compile a list of ANDed SelectorRequirements against the snapshot's
    demand-driven label vocab -> (req_all, any_groups, forbid, unsat).
    Semantics per NodeSelectorRequirementsAsSelector + labels.Selector.Matches
    (predicates.go:625-647): In -> pair membership, Exists/Gt/Lt -> expansion
    over the values present on nodes, NotIn/DoesNotExist -> forbidden pairs
    (absent key matches)."""
    req_all: List[int] = []
    any_groups: List[List[int]] = []
    forbid: List[int] = []
    unsat = not match_expressions
    for r in match_expressions:
        op = SelectorOperator(r.operator)
        if op == SelectorOperator.IN:
            # intern every referenced pair; a pair no node carries is an
            # all-zero column, so matching fails naturally
            idxs = [snap.ensure_label_pair(r.key, v) for v in r.values]
            if not idxs:
                unsat = True
            elif len(idxs) == 1:
                req_all.append(idxs[0])
            else:
                any_groups.append(idxs)
        elif op == SelectorOperator.EXISTS:
            vals = snap.node_values_for_key(r.key)
            if not vals:
                unsat = True  # no node has the key at snapshot time
            else:
                any_groups.append(
                    [snap.ensure_label_pair(r.key, v) for v in vals])
        elif op == SelectorOperator.DOES_NOT_EXIST:
            forbid.extend(snap.ensure_label_pair(r.key, v)
                          for v in snap.node_values_for_key(r.key))
        elif op == SelectorOperator.NOT_IN:
            vals = set(snap.node_values_for_key(r.key))
            forbid.extend(snap.ensure_label_pair(r.key, v)
                          for v in r.values if v in vals)
        elif op in (SelectorOperator.GT, SelectorOperator.LT):
            try:
                rhs = int(r.values[0]) if r.values else None
            except ValueError:
                rhs = None
            if rhs is None:
                unsat = True
            else:
                idxs = []
                for val in snap.node_values_for_key(r.key):
                    try:
                        lhs = int(val)
                    except ValueError:
                        continue
                    if (lhs > rhs) if op == SelectorOperator.GT else (lhs < rhs):
                        idxs.append(snap.ensure_label_pair(r.key, val))
                if not idxs:
                    unsat = True
                else:
                    any_groups.append(idxs)
    return (req_all, any_groups, forbid, unsat)


class PodBatch:
    """Dense encoding of a list of pending pods against a snapshot's vocabs.

    Selector compilation (node_selector + required node affinity): each pod
    gets up to T disjuncts (OR of ANDed terms — predicates.go:625
    nodeMatchesNodeSelectorTerms). Each disjunct is compiled to:
      req_all  [T, L]  pairs that must ALL be present (match_labels / In-1)
      req_any  [T, A, L]  groups where >=1 pair must be present
                          (In-many / Exists / Gt / Lt via vocab expansion)
      forbid   [T, L]  pairs that must NOT be present (NotIn / DoesNotExist)
      term_valid [T]   real term (False rows auto-fail so OR ignores them)
    An UNSATISFIABLE requirement (e.g. In with values absent from the vocab)
    makes the term auto-fail via a sentinel in req_any counts.

    Pods whose node_selector/affinity is empty get sel_any_term=False and
    match all nodes, matching podMatchesNodeLabels (predicates.go:640-647).
    """

    def __init__(self, pods: Sequence[Pod], snap: ClusterSnapshot,
                 max_terms: int = 4, max_any: int = 2, max_pref: int = 8):
        self.pods = list(pods)
        P = len(self.pods)
        if snap._shape_sig is None:
            raise RuntimeError("ClusterSnapshot.refresh() must run before PodBatch")
        T = snap.taints_sched.shape[1]
        Rr = snap.num_resources
        self.req = np.zeros((P, Rr), dtype=np.int32)
        self.nonzero = np.zeros((P, 2), dtype=np.int32)
        self.zero_req = np.zeros(P, dtype=bool)
        # pod requests an extended resource NO node advertises -> can never
        # fit anywhere (alloc 0 < request on every node)
        self.impossible = np.zeros(P, dtype=bool)
        self.best_effort = np.zeros(P, dtype=bool)
        self.ports = np.full((P, MAX_PORTS_PER_POD), -1, dtype=np.int32)
        self.intolerated = np.ones((P, T), dtype=np.int8)  # sched-taints NOT tolerated
        self.intolerated_pref = np.ones((P, T), dtype=np.int8)
        self.host_required = np.full(P, -1, dtype=np.int32)  # PodFitsHost node idx
        self.has_host = np.zeros(P, dtype=bool)
        self.needs_host_check = np.zeros(P, dtype=bool)
        # which host-check causes are NOT derivable from node labels alone
        # (live-NodeInfo ports, score-affecting preference overflow) — the
        # wave path can absorb the label-pure remainder as a static fit
        # column (host_static_fit) but these must stay on the exact oracle
        self.host_check_dynamic = np.zeros(P, dtype=bool)

        # selector structures — sized by actual usage, min 1 term. Compiling
        # interns referenced label pairs into the snapshot's demand-driven
        # vocab, so the label matrix is finalized only afterwards.
        n_terms = 1
        n_any = 1
        n_pref = 1
        compiled = []
        pref_compiled = []
        for pod in self.pods:
            terms = self._compile_selector(pod, snap)
            compiled.append(terms)
            n_terms = max(n_terms, len(terms))
            for t in terms:
                n_any = max(n_any, len(t[1]))
            prefs = self._compile_preferred(pod, snap)
            pref_compiled.append(prefs)
            n_pref = max(n_pref, len(prefs))
            for _, comp in prefs:
                if comp is not None:
                    n_any = max(n_any, len(comp[1]))
            for c in pod.containers:
                if c.image:
                    snap.ensure_image(c.image)
        # volume compilation: interns conflict/PD keys and (for VolumeZone)
        # zone label pairs / (for VolumeNode) PV-affinity pairs into the
        # demand-driven vocabs BEFORE the matrices are finalized
        from kubernetes_tpu.utils import features as featmod
        vol_node_on = featmod.enabled("PersistentLocalVolumes")
        vol_compiled = []
        for p, pod in enumerate(self.pods):
            if not pod.volumes:
                vol_compiled.append(None)
                continue
            entry = {"err": False, "zone_err": False, "conf": [], "pd": [],
                     "zone": [], "pvaff": None}
            for key, ro in volmod.pod_conflict_keys(pod):
                entry["conf"].append((snap.ensure_conflict_key(key), ro))
            for k, vid in volmod.pd_filter_ids(pod, snap.volume_ctx):
                entry["pd"].append((k, snap.ensure_pd_id(k, vid)))
            try:
                for zk, zv in volmod.zone_constraints(pod, snap.volume_ctx):
                    if zv == "":
                        # node missing the key passes in the reference
                        # ("" == ""); exact host path handles this rarity
                        self.needs_host_check[p] = True
                        continue
                    entry["zone"].append(snap.ensure_label_pair(zk, zv))
            except volmod.UnresolvedVolume:
                # VolumeZone errors AFTER its no-zone-labels fast-path
                # (predicates.go:425-430): fails zone-labeled nodes only
                entry["zone_err"] = True
            if vol_node_on:
                try:
                    reqs = volmod.pv_affinity_requirements(pod, snap.volume_ctx)
                    if reqs:
                        comp = compile_requirements(reqs, snap)
                        entry["pvaff"] = comp
                        n_any = max(n_any, len(comp[1]))
                except volmod.UnresolvedVolume:
                    # VolumeNode errors unconditionally -> schedule fails
                    entry["err"] = True
            vol_compiled.append(entry)
        n_terms = min(n_terms, max_terms)
        n_any = min(n_any, max_any)
        n_pref = min(n_pref, max_pref)
        L = snap.finalize_labels()
        I = snap.finalize_images()
        Vc, Vpd = snap.finalize_volumes()
        self.sel_req_all = np.zeros((P, n_terms, L), dtype=np.int8)
        self.sel_req_any = np.zeros((P, n_terms, n_any, L), dtype=np.int8)
        self.sel_forbid = np.zeros((P, n_terms, L), dtype=np.int8)
        self.sel_term_valid = np.zeros((P, n_terms), dtype=bool)
        self.sel_any_used = np.zeros((P, n_terms, n_any), dtype=bool)
        self.sel_unsat = np.zeros((P, n_terms), dtype=bool)
        self.has_selector = np.zeros(P, dtype=bool)
        # preferred node-affinity terms (NodeAffinityPriority,
        # node_affinity.go:36-77): weight + compiled selector per term; a term
        # with no expressions matches ALL nodes (pref_empty)
        self.pref_req_all = np.zeros((P, n_pref, L), dtype=np.int8)
        self.pref_req_any = np.zeros((P, n_pref, n_any, L), dtype=np.int8)
        self.pref_forbid = np.zeros((P, n_pref, L), dtype=np.int8)
        self.pref_any_used = np.zeros((P, n_pref, n_any), dtype=bool)
        self.pref_valid = np.zeros((P, n_pref), dtype=bool)
        self.pref_unsat = np.zeros((P, n_pref), dtype=bool)
        self.pref_empty = np.zeros((P, n_pref), dtype=bool)
        self.pref_weight = np.zeros((P, n_pref), dtype=np.int32)
        # NodePreferAvoidPods: index into the avoid vocab, -1 = not RC/RS-owned
        self.avoid_idx = np.full(P, -1, dtype=np.int32)
        # ImageLocality: per-image container counts
        self.img_count = np.zeros((P, I), dtype=np.int32)
        # volume predicates: NoDiskConflict hard (conflicts with any
        # presence) / ro (conflicts with read-write presence) key rows,
        # MaxPDVolumeCount id rows + per-kind distinct counts, VolumeZone
        # required label pairs, VolumeNode compiled PV affinity (one conjunct
        # — PV terms are ANDed, util.go:202)
        self.vol_hard = np.zeros((P, Vc), dtype=np.int8)
        self.vol_ro = np.zeros((P, Vc), dtype=np.int8)
        self.pd_req = np.zeros((P, Vpd), dtype=np.int8)
        self.pd_req_count = np.zeros((P, 3), dtype=np.int32)
        self.vz_req = np.zeros((P, L), dtype=np.int8)
        self.vz_err = np.zeros(P, dtype=bool)
        self.pvaff_req_all = np.zeros((P, L), dtype=np.int8)
        self.pvaff_req_any = np.zeros((P, n_any, L), dtype=np.int8)
        self.pvaff_forbid = np.zeros((P, L), dtype=np.int8)
        self.pvaff_any_used = np.zeros((P, n_any), dtype=bool)
        self.pvaff_unsat = np.zeros(P, dtype=bool)
        self.pvaff_has = np.zeros(P, dtype=bool)

        for p, pod in enumerate(self.pods):
            self._encode_pod(p, pod, snap, compiled[p], n_terms, n_any)
            self._encode_pref(p, pod, snap, pref_compiled[p], n_pref, n_any)
            if pod.owner_kind in ("ReplicationController", "ReplicaSet"):
                self.avoid_idx[p] = snap.avoid_vocab.get(pod.owner_kind,
                                                         pod.owner_uid)
            for c in pod.containers:
                if c.image:
                    idx = snap.image_vocab.get(c.image, "")
                    if idx >= 0:
                        self.img_count[p, idx] += 1
            self._encode_volumes(p, vol_compiled[p], n_any)

    # -------------------------------------------------------------- helpers

    def _compile_selector(self, pod: Pod, snap: ClusterSnapshot):
        """-> list of (req_all_idx, [any_idx_groups], forbid_idx, unsat)."""
        terms: List[NodeSelectorTerm] = []
        simple: List[SelectorRequirement] = [
            SelectorRequirement(k, SelectorOperator.IN, [v])
            for k, v in sorted(pod.node_selector.items())
        ]
        na = pod.affinity.node_affinity if pod.affinity else None
        if na is not None and na.required_terms is not None:
            # affinity terms are ORed with each other but ANDed with the
            # plain node_selector (predicates.go:640 "requirements in both")
            for t in na.required_terms:
                terms.append(NodeSelectorTerm(simple + list(t.match_expressions)))
            if not na.required_terms:
                # empty term list matches no nodes (predicates.go:646 case 2-3)
                terms = [NodeSelectorTerm([SelectorRequirement(
                    "\x00unsat", SelectorOperator.IN, [])])]
        elif simple:
            terms = [NodeSelectorTerm(simple)]
        return [compile_requirements(term.match_expressions, snap)
                for term in terms]

    def _compile_preferred(self, pod: Pod, snap: ClusterSnapshot):
        """-> [(weight, compiled-or-None)] for preferred node-affinity terms;
        None = empty term (matches every node, node_affinity.go:51)."""
        na = pod.affinity.node_affinity if pod.affinity else None
        out = []
        for weight, term in (na.preferred_terms if na else []):
            if weight == 0:
                continue  # node_affinity.go:57
            if not term.match_expressions:
                out.append((weight, None))
            else:
                out.append((weight,
                            compile_requirements(term.match_expressions, snap)))
        return out

    def _encode_pod(self, p: int, pod: Pod, snap: ClusterSnapshot, terms,
                    n_terms: int, n_any: int) -> None:
        req = pod.resource_request()
        unknown: List[str] = []
        self.req[p] = snap.resource_row(
            milli_cpu=req.milli_cpu, memory=req.memory, gpu=req.nvidia_gpu,
            scratch=req.storage_scratch, overlay=req.storage_overlay,
            extended=req.extended, up=True, width=snap.num_resources,
            unknown=unknown)
        if unknown:
            self.impossible[p] = True
        ncpu, nmem = pod.nonzero_request()
        self.nonzero[p, 0] = ncpu
        self.nonzero[p, 1] = snap.quant_mem(nmem, up=True)
        # PodFitsResources early-exit: all-zero request only checks pod count
        # (predicates.go:576-578)
        self.zero_req[p] = (
            req.milli_cpu == 0 and req.memory == 0 and req.nvidia_gpu == 0
            and req.storage_scratch == 0 and req.storage_overlay == 0
            and not req.extended)
        self.best_effort[p] = pod.is_best_effort()

        for j, port in enumerate(pod.used_ports()[:MAX_PORTS_PER_POD]):
            self.ports[p, j] = port
        if len(pod.used_ports()) > MAX_PORTS_PER_POD:
            self.needs_host_check[p] = True
            self.host_check_dynamic[p] = True  # HostPorts needs live pods

        if pod.node_name:
            self.has_host[p] = True
            self.host_required[p] = snap.node_index.get(pod.node_name, -1)

        # inter-pod affinity no longer forces the host path: the topology-
        # incidence kernel (ops/affinity.py) evaluates it in the placement
        # scan; only term-slot overflow routes to the oracle (the engine
        # marks those classes from AffinityData.overflow)

        # tolerations -> which vocab taints remain INtolerated
        for t_idx, (tkey, tpack) in enumerate(snap.taint_vocab.items()):
            tval, _, teff = tpack.partition("\x00")
            taint = Taint(tkey, tval, TaintEffect(teff))
            tolerated = any(tol.tolerates(taint) for tol in pod.tolerations)
            if tolerated:
                self.intolerated[p, t_idx] = 0
                self.intolerated_pref[p, t_idx] = 0
        # PodToleratesNodeTaints only filters NoSchedule|NoExecute
        # (predicates.go:1241-1246); PreferNoSchedule feeds the
        # TaintToleration priority instead (taint_toleration.go).

        if len(terms) > n_terms:
            # too many OR terms for the static shape — over-approximate
            # (pass-all) and verify exactly host-side
            self.needs_host_check[p] = True
            terms = []
        for t, (req_all, any_groups, forbid, unsat) in enumerate(terms):
            self.sel_term_valid[p, t] = True
            self.has_selector[p] = True
            if len(any_groups) > n_any:
                self.needs_host_check[p] = True
                any_groups = []
            if unsat:
                self.sel_unsat[p, t] = True
            for i in req_all:
                self.sel_req_all[p, t, i] = 1
            for i in forbid:
                self.sel_forbid[p, t, i] = 1
            for a, group in enumerate(any_groups):
                self.sel_any_used[p, t, a] = True
                for i in group:
                    self.sel_req_any[p, t, a, i] = 1

    def _encode_volumes(self, p: int, entry, n_any: int) -> None:
        if entry is None:
            return
        if entry["err"]:
            # UnresolvedVolume from VolumeNode: predicate error fails the
            # whole schedule attempt for this pod -> unplaceable this round
            self.impossible[p] = True
            return
        if entry["zone_err"]:
            self.vz_err[p] = True
        for idx, ro in entry["conf"]:
            if ro:
                self.vol_ro[p, idx] = 1
            else:
                self.vol_hard[p, idx] = 1
        seen = [set(), set(), set()]
        for k, idx in entry["pd"]:
            self.pd_req[p, idx] = 1
            seen[k].add(idx)
        self.pd_req_count[p] = [len(s) for s in seen]
        for idx in entry["zone"]:
            self.vz_req[p, idx] = 1
        comp = entry["pvaff"]
        if comp is not None:
            req_all, any_groups, forbid, unsat = comp
            self.pvaff_has[p] = True
            if len(any_groups) > n_any:
                self.needs_host_check[p] = True
                any_groups = []
            if unsat:
                self.pvaff_unsat[p] = True
            for i in req_all:
                self.pvaff_req_all[p, i] = 1
            for i in forbid:
                self.pvaff_forbid[p, i] = 1
            for a, group in enumerate(any_groups):
                self.pvaff_any_used[p, a] = True
                for i in group:
                    self.pvaff_req_any[p, a, i] = 1

    def _encode_pref(self, p: int, pod: Pod, snap: ClusterSnapshot, prefs,
                     n_pref: int, n_any: int) -> None:
        if len(prefs) > n_pref:
            # too many preferred terms for static shape: host-exact path
            self.needs_host_check[p] = True
            # score-affecting — a fit column can't express the missing
            # preference weights, so no static-column absorption
            self.host_check_dynamic[p] = True
            prefs = prefs[:0]
        for t, (weight, comp) in enumerate(prefs):
            self.pref_valid[p, t] = True
            self.pref_weight[p, t] = weight
            if comp is None:
                self.pref_empty[p, t] = True
                continue
            req_all, any_groups, forbid, unsat = comp
            if len(any_groups) > n_any:
                self.needs_host_check[p] = True
                self.host_check_dynamic[p] = True  # score-affecting
                any_groups = []
            if unsat:
                self.pref_unsat[p, t] = True
            for i in req_all:
                self.pref_req_all[p, t, i] = 1
            for i in forbid:
                self.pref_forbid[p, t, i] = 1
            for a, group in enumerate(any_groups):
                self.pref_any_used[p, t, a] = True
                for i in group:
                    self.pref_req_any[p, t, a, i] = 1

    def host_static_fit(self, p: int, snap: ClusterSnapshot):
        """Exact label-pure host-fit row [n_pad] for pod p over the
        snapshot's raw per-node label maps (ISSUE 18) — the static
        column a host-check class rides the wave with instead of
        flushing the pipeline. Evaluates the FULL predicates the fused
        eval over-approximated (selector shape overflow, VolumeZone
        ""-valued constraints, PV-affinity any-group overflow) straight
        from the reference semantics (oracle.pod_matches_node_selector,
        volumes.node_zone_check, NoVolumeNodeConflict), so ANDing it
        with the device's superset column yields the exact predicate.

        Returns None when the pod's host requirement is NOT derivable
        from labels alone (live-NodeInfo ports, score-affecting pref
        overflow, unresolvable PVs) — the caller must keep that class
        on the exact harvest-tail path. Padding rows are left True;
        the validity mask excludes them downstream.
        """
        if self.host_check_dynamic[p]:
            return None
        from kubernetes_tpu.utils import features as featmod
        pod = self.pods[p]
        zcons = None
        pv_reqs = ()
        if pod.volumes:
            try:
                zcons = volmod.zone_constraints(pod, snap.volume_ctx)
            except volmod.UnresolvedVolume:
                zcons = None  # vz_err: the device column handles it exactly
            if featmod.enabled("PersistentLocalVolumes"):
                try:
                    pv_reqs = volmod.pv_affinity_requirements(
                        pod, snap.volume_ctx)
                except volmod.UnresolvedVolume:
                    return None  # reference fails the attempt: exact path
        na = pod.affinity.node_affinity if pod.affinity else None
        fit = np.ones(snap.valid.shape[0], dtype=bool)
        for i in range(len(snap.node_names)):
            labels = snap._row_labels[i]
            ok = True
            for k, v in pod.node_selector.items():
                if labels.get(k) != v:
                    ok = False
                    break
            if ok and na is not None and na.required_terms is not None:
                # ORed terms; empty list matches nothing
                ok = any(t.matches_labels(labels)
                         for t in na.required_terms)
            if ok and zcons:
                node_zone = {k: v for k, v in labels.items()
                             if k in (volmod.ZONE_LABEL,
                                      volmod.REGION_LABEL)}
                for k, v in (node_zone and zcons or ()):
                    if node_zone.get(k, "") != v:
                        ok = False
                        break
            if ok and pv_reqs:
                ok = all(r.matches_labels(labels) for r in pv_reqs)
            fit[i] = ok
        return fit

    def __len__(self) -> int:
        return len(self.pods)
