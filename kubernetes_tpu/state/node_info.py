"""Per-node aggregate state.

TPU-native analog of schedulercache.NodeInfo (reference:
plugin/pkg/scheduler/schedulercache/node_info.go:34-75): the authoritative
host-side aggregate of everything the placement kernels need about one node —
pods assigned (incl. assumed), requested and nonzero-requested resource sums,
used host ports, and a monotonically increasing generation counter that drives
incremental snapshot refresh (node_info.go generation is bumped on every
mutation; the cache's UpdateNodeNameToInfoMap at cache.go:79 clones only nodes
whose generation moved — our tensor snapshot does the same per-column delta
upload, see kubernetes_tpu/state/snapshot.py).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

from kubernetes_tpu.api.types import Node, Pod, Resource


class NodeInfo:
    __slots__ = (
        "node",
        "pods",
        "pods_with_affinity",
        "requested",
        "nonzero_cpu",
        "nonzero_mem",
        "used_ports",
        "vol_count",
        "prio_usage",
        "generation",
        "spec_generation",
        "ports_generation",
    )

    def __init__(self, node: Optional[Node] = None):
        self.node: Optional[Node] = node
        self.pods: List[Pod] = []
        # pods carrying any pod (anti-)affinity — the reference tracks these
        # separately (node_info.go PodsWithAffinity) so the symmetry checks
        # don't scan every pod
        self.pods_with_affinity: List[Pod] = []
        self.requested = Resource()
        self.nonzero_cpu = 0
        self.nonzero_mem = 0
        self.used_ports: Set[int] = set()
        # volume-bearing pods on this node — a counter so the snapshot's
        # columnar dynamic-row writer can skip the per-pod volume walk on
        # the (overwhelmingly common) volume-free node
        self.vol_count = 0
        # per-PRIORITY resource aggregate: priority -> [milli_cpu, memory,
        # count] over this node's pods (assumed included). The snapshot's
        # priority-band columns read this per dirty row, so the wave-path
        # victim scan (ISSUE 14) never walks pod lists; one dict op per
        # add/remove keeps it exact.
        self.prio_usage: Dict[int, list] = {}
        # generation: any mutation; spec_generation: node object (labels,
        # taints, allocatable, conditions) changed; ports_generation: the
        # used-ports set changed. The snapshot diffs each independently so a
        # plain pod add/remove only rewrites the small dynamic arrays.
        self.generation = 0
        self.spec_generation = 0
        self.ports_generation = 0

    # -- mutation (mirrors node_info.go addPod:302 / removePod:330) ---------

    def add_pod(self, pod: Pod) -> None:
        req = pod.resource_request()
        ncpu, nmem = pod.nonzero_request()
        self.add_pod_precomputed(pod, req, ncpu, nmem, pod.used_ports())

    def add_pod_precomputed(self, pod: Pod, req: Resource, ncpu: int,
                            nmem: int, ports: List[int]) -> None:
        """add_pod with the derived quantities supplied by the caller — the
        bulk-assume path computes them once per equivalence class instead of
        once per pod (30k identical pods -> one resource_request walk)."""
        self.requested.add(req)
        self.nonzero_cpu += ncpu
        self.nonzero_mem += nmem
        if ports:
            self.used_ports.update(ports)
            self.ports_generation += 1
        if pod.volumes:
            self.vol_count += 1
        u = self.prio_usage.get(pod.priority)
        if u is None:
            self.prio_usage[pod.priority] = [req.milli_cpu, req.memory, 1]
        else:
            u[0] += req.milli_cpu
            u[1] += req.memory
            u[2] += 1
        self.pods.append(pod)
        if pod.affinity is not None and (pod.affinity.pod_affinity is not None
                                         or pod.affinity.pod_anti_affinity is not None):
            self.pods_with_affinity.append(pod)
        self.generation += 1

    def add_pods_same_class(self, pods: List[Pod], req: Resource, ncpu: int,
                            nmem: int, ports: List[int]) -> None:
        """add_pod_precomputed for a RUN of spec-equal pods landing on this
        node: one scaled resource update + one list extend instead of
        len(pods) Python-object walks — the columnar half of the drain's
        assume phase (ISSUE 2). Semantically identical to calling
        add_pod_precomputed per pod, in order."""
        n = len(pods)
        if n == 0:
            return
        if n == 1:
            self.add_pod_precomputed(pods[0], req, ncpu, nmem, ports)
            return
        r = self.requested
        r.milli_cpu += req.milli_cpu * n
        r.memory += req.memory * n
        r.nvidia_gpu += req.nvidia_gpu * n
        r.storage_scratch += req.storage_scratch * n
        r.storage_overlay += req.storage_overlay * n
        for k, v in req.extended.items():
            r.extended[k] = r.extended.get(k, 0) + v * n
        self.nonzero_cpu += ncpu * n
        self.nonzero_mem += nmem * n
        if ports:
            self.used_ports.update(ports)
            self.ports_generation += 1
        if pods[0].volumes:
            self.vol_count += n
        u = self.prio_usage.get(p_prio := pods[0].priority)
        if u is None:
            self.prio_usage[p_prio] = [req.milli_cpu * n, req.memory * n, n]
        else:
            u[0] += req.milli_cpu * n
            u[1] += req.memory * n
            u[2] += n
        self.pods.extend(pods)
        p0 = pods[0]
        if p0.affinity is not None and (p0.affinity.pod_affinity is not None
                                        or p0.affinity.pod_anti_affinity is not None):
            self.pods_with_affinity.extend(pods)
        self.generation += 1

    def remove_pod(self, pod: Pod) -> bool:
        key = pod.key()
        for i, p in enumerate(self.pods):
            if p.key() == key:
                del self.pods[i]
                self.pods_with_affinity = [
                    q for q in self.pods_with_affinity if q.key() != key]
                req = p.resource_request()
                self.requested.sub(req)
                if p.volumes:
                    self.vol_count -= 1
                u = self.prio_usage.get(p.priority)
                if u is not None:
                    u[0] -= req.milli_cpu
                    u[1] -= req.memory
                    u[2] -= 1
                    if u[2] <= 0:
                        del self.prio_usage[p.priority]
                ncpu, nmem = p.nonzero_request()
                self.nonzero_cpu -= ncpu
                self.nonzero_mem -= nmem
                if p.used_ports():
                    # rebuild ports (another pod may still hold the same port —
                    # the reference keeps a map and re-adds; rebuilding is exact)
                    self.used_ports = set()
                    for q in self.pods:
                        self.used_ports.update(q.used_ports())
                    self.ports_generation += 1
                self.generation += 1
                return True
        return False

    def set_node(self, node: Node) -> None:
        self.node = node
        self.generation += 1
        self.spec_generation += 1

    def allocatable(self) -> Resource:
        return self.node.allocatable if self.node else Resource()

    def allowed_pod_number(self) -> int:
        return self.node.allowed_pod_number if self.node else 0

    def clone_shallow(self) -> "NodeInfo":
        out = NodeInfo(self.node)
        out.pods = list(self.pods)
        out.pods_with_affinity = list(self.pods_with_affinity)
        out.requested = self.requested.clone()
        out.nonzero_cpu = self.nonzero_cpu
        out.nonzero_mem = self.nonzero_mem
        out.used_ports = set(self.used_ports)
        out.vol_count = self.vol_count
        out.prio_usage = {k: list(v) for k, v in self.prio_usage.items()}
        out.generation = self.generation
        out.spec_generation = self.spec_generation
        out.ports_generation = self.ports_generation
        return out


def node_info_map(nodes: List[Node], pods: List[Pod]) -> Dict[str, NodeInfo]:
    """Build a fresh name->NodeInfo map from raw objects (bound pods only)."""
    out: Dict[str, NodeInfo] = {n.name: NodeInfo(n) for n in nodes}
    for p in pods:
        if p.node_name and p.node_name in out:
            out[p.node_name].add_pod(p)
    return out
