"""Equivalence classes over pending pods: tensorize unique specs once.

The reference de-duplicates predicate work with the equivalence cache
(plugin/pkg/scheduler/core/equivalence_cache.go:54 — per-node LRU keyed by
the equivalence hash of the pod's owning controller, :183 getEquivalenceHash).
The tensor analog is stronger and simpler: pods are grouped by a canonical
hash of every spec field the kernels read, the PodBatch encoding runs once
per CLASS instead of once per pod, and per-pod rows are recovered on device
with a single gather (`arrays[class_of]`). A 30k-pod deployment storm of one
template costs one row of host-side encoding instead of 30k.

Unlike the reference's controller-ref hash (which assumes pods of one
ReplicaSet are interchangeable), the class key here is exact: two pods share
a class only if every feature that can influence predicates, priorities, or
host-path routing (labels/namespace for affinity symmetry and spreading)
is identical, so dedup can never change a scheduling outcome.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.state.snapshot import ClusterSnapshot, PodBatch


def _canon_reqs(reqs) -> tuple:
    return tuple((r.key, str(r.operator), tuple(r.values)) for r in reqs)


def _canon_nsterms(terms) -> Optional[tuple]:
    if terms is None:
        return None
    return tuple(_canon_reqs(t.match_expressions) for t in terms)


def _canon_label_selector(ls) -> Optional[tuple]:
    if ls is None:
        return None
    return (tuple(sorted(ls.match_labels.items())),
            _canon_reqs(ls.match_expressions))


def _canon_pod_term(t) -> tuple:
    return (_canon_label_selector(t.label_selector), tuple(t.namespaces),
            t.topology_key)


def _canon_pod_affinity(pa) -> Optional[tuple]:
    if pa is None:
        return None
    return (tuple(_canon_pod_term(t) for t in pa.required_terms),
            tuple((w, _canon_pod_term(t)) for w, t in pa.preferred_terms))


def _canon_node_affinity(na) -> Optional[tuple]:
    if na is None:
        return None
    return (_canon_nsterms(na.required_terms),
            tuple((w, _canon_reqs(t.match_expressions))
                  for w, t in na.preferred_terms))


def _canon_affinity(a) -> Optional[tuple]:
    if a is None:
        return None
    return (_canon_node_affinity(a.node_affinity),
            _canon_pod_affinity(a.pod_affinity),
            _canon_pod_affinity(a.pod_anti_affinity))


def _canon_volume(v) -> tuple:
    return (v.name, str(v.kind), v.volume_id, v.read_only,
            tuple(v.monitors), v.pool, v.image)


def _canon_container(c) -> tuple:
    # limits matter: is_best_effort() reads them (types.py) and best_effort
    # drives the CheckNodeMemoryPressure predicate. Empty/singleton dicts
    # skip the sort — a one-element items() tuple IS its sorted form, and
    # this runs 30k times per drain round.
    req = c.requests
    lim = c.limits
    return (c.image,
            tuple(req.items()) if len(req) < 2 else tuple(sorted(req.items())),
            tuple(lim.items()) if len(lim) < 2 else tuple(sorted(lim.items())),
            tuple((p.host_port, p.protocol) for p in c.ports) if c.ports
            else ())


def pod_class_key(pod: Pod) -> tuple:
    """Canonical spec tuple covering every field read by tensorization
    (snapshot.PodBatch), the kernels, and host-path routing. Name/uid/rv are
    deliberately excluded — identity never affects placement.

    Memoized per pod object: building the nested tuple costs ~6us and the
    drain keys 30k pods per round. The only spec field the scheduler
    mutates IN PLACE after keying is node_name (engine assume), so the
    cache is guarded on its identity; every other mutation path in the
    control plane goes through dataclasses.replace / fresh decode — and
    the one shallow-copy hop (scheduler._queue_copy, the arrival-storm
    queue admission) DROPS this memo explicitly — so a stale class key
    never crosses an object boundary."""
    cached = pod.__dict__.get("_class_key")
    if cached is not None and cached[0] is pod.node_name:
        return cached[1]
    key = _pod_class_key(pod)
    pod.__dict__["_class_key"] = (pod.node_name, key)
    return key


def _pod_class_key(pod: Pod) -> tuple:
    labels = pod.labels
    sel = pod.node_selector
    return (
        pod.namespace,
        tuple(labels.items()) if len(labels) < 2
        else tuple(sorted(labels.items())),
        tuple(_canon_container(c) for c in pod.containers),
        tuple(_canon_volume(v) for v in pod.volumes) if pod.volumes else (),
        pod.node_name,
        tuple(sel.items()) if len(sel) < 2 else tuple(sorted(sel.items())),
        _canon_affinity(pod.affinity),
        tuple(pod.tolerations) if pod.tolerations else (),
        pod.priority,
        pod.owner_kind,
        pod.owner_uid,
        pod.deleted,
    )


class ClassBatch:
    """Pending pods grouped into spec-equivalence classes.

    reps_batch  PodBatch over one representative pod per class (C rows)
    pod_class   int32 [P] — class index of each input pod
    pods        the original pod list (order preserved)
    """

    def __init__(self, pods: Sequence[Pod], snap: ClusterSnapshot, **kw):
        self.pods: List[Pod] = list(pods)
        index: Dict[tuple, int] = {}
        reps: List[Pod] = []
        pod_class = np.empty(len(self.pods), dtype=np.int32)
        for i, p in enumerate(self.pods):
            k = pod_class_key(p)
            c = index.get(k)
            if c is None:
                c = len(reps)
                index[k] = c
                reps.append(p)
            pod_class[i] = c
        self.reps: List[Pod] = reps
        self.pod_class = pod_class
        self.reps_batch = PodBatch(reps, snap, **kw)

    @property
    def num_classes(self) -> int:
        return len(self.reps)

    def mark_host_check_class(self, c: int) -> None:
        self.reps_batch.needs_host_check[c] = True

    def __len__(self) -> int:
        return len(self.pods)
