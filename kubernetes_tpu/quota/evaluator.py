"""Quota evaluators: object -> resource usage deltas.

Mirror of pkg/quota/evaluator/core (pod.go PodUsageFunc, services.go,
persistent_volume_claims.go, the generic object-count evaluators) consumed by
both the resourcequota admission controller
(plugin/pkg/admission/resourcequota) and the quota reconciliation controller
(pkg/controller/resourcequota). Units: cpu millicores, memory bytes, counts.
"""

from __future__ import annotations

from typing import Dict, List

from kubernetes_tpu.api.types import Pod

# quota resource names (pkg/api/types.go ResourceName constants)
PODS = "pods"
CPU = "cpu"  # == requests.cpu
MEMORY = "memory"
REQUESTS_CPU = "requests.cpu"
REQUESTS_MEMORY = "requests.memory"
LIMITS_CPU = "limits.cpu"
LIMITS_MEMORY = "limits.memory"

COUNT_KINDS = {
    "Service": "services",
    "ReplicationController": "replicationcontrollers",
    "ResourceQuota": "resourcequotas",
    "Secret": "secrets",
    "ConfigMap": "configmaps",
    "PersistentVolumeClaim": "persistentvolumeclaims",
}


def pod_usage(pod: Pod) -> Dict[str, int]:
    """PodUsageFunc (pkg/quota/evaluator/core/pods.go): requests + limits
    summed across containers; pods count 1. Terminal-phase pods consume no
    quota (filtered by the caller via is_terminal)."""
    cpu = mem = lcpu = lmem = 0
    for c in pod.containers:
        cpu += c.requests.get("cpu", 0)
        mem += c.requests.get("memory", 0)
        lcpu += c.limits.get("cpu", 0)
        lmem += c.limits.get("memory", 0)
    usage = {PODS: 1}
    if cpu:
        usage[CPU] = cpu
        usage[REQUESTS_CPU] = cpu
    if mem:
        usage[MEMORY] = mem
        usage[REQUESTS_MEMORY] = mem
    if lcpu:
        usage[LIMITS_CPU] = lcpu
    if lmem:
        usage[LIMITS_MEMORY] = lmem
    return usage


def is_terminal(pod: Pod) -> bool:
    return pod.phase in ("Succeeded", "Failed")


def object_count_usage(kind: str) -> Dict[str, int]:
    name = COUNT_KINDS.get(kind)
    return {name: 1} if name else {}


def usage_for(kind: str, obj) -> Dict[str, int]:
    if kind == "Pod":
        if is_terminal(obj):
            return {}
        return pod_usage(obj)
    return object_count_usage(kind)


def quota_scopes_match(scopes: List[str], kind: str, obj) -> bool:
    """Scope selectors (pods.go podMatchesScopeFunc): BestEffort /
    NotBestEffort / Terminating / NotTerminating; non-pod kinds match only
    scope-less quotas."""
    if not scopes:
        return True
    if kind != "Pod":
        return False
    for s in scopes:
        if s == "BestEffort" and not obj.is_best_effort():
            return False
        if s == "NotBestEffort" and obj.is_best_effort():
            return False
        if s == "Terminating" and not getattr(obj, "deleted", False):
            return False
        if s == "NotTerminating" and getattr(obj, "deleted", False):
            return False
    return True


def add_usage(into: Dict[str, int], delta: Dict[str, int]) -> None:
    for k, v in delta.items():
        into[k] = into.get(k, 0) + v


def sub_usage(into: Dict[str, int], delta: Dict[str, int]) -> None:
    for k, v in delta.items():
        into[k] = into.get(k, 0) - v


def exceeds(hard: Dict[str, int], used: Dict[str, int],
            delta: Dict[str, int]) -> List[str]:
    """Which constrained resources would go over hard limits if delta were
    admitted (resource_access.go CheckRequest semantics)."""
    over = []
    for k, lim in hard.items():
        if k in delta and used.get(k, 0) + delta[k] > lim:
            over.append(k)
    return over
