from kubernetes_tpu.quota.evaluator import (  # noqa: F401
    pod_usage,
    object_count_usage,
    usage_for,
    quota_scopes_match,
    add_usage,
    sub_usage,
    exceeds,
)
