"""Scheduler Policy API: declarative predicate/priority/extender config.

Mirror of the reference's Policy types (plugin/pkg/scheduler/api/types.go:38-155
and the v1 JSON mirror api/v1/types.go) parsed from the same JSON format the
reference accepts via --policy-config-file / --policy-configmap
(factory.go:619 CreateFromConfig). Backward compatibility of this format
matters (compatibility_test.go guards it upstream; tests/test_policy.py here).

Also hosts the algorithm-provider registry: the named default
predicate/priority sets (algorithmprovider/defaults/defaults.go:118,191 —
DefaultProvider; :65 ClusterAutoscalerProvider swaps LeastRequested for
MostRequested).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api.types import MAX_PRIORITY

MAX_WEIGHT = MAX_PRIORITY * 100  # validation.go: weight must be < MaxWeight


@dataclass
class LabelsPresence:
    labels: List[str] = field(default_factory=list)
    presence: bool = True


@dataclass
class ServiceAffinityArgs:
    labels: List[str] = field(default_factory=list)


@dataclass
class PredicatePolicy:
    name: str
    # argument (api/types.go:67-77): only one of these set
    service_affinity: Optional[ServiceAffinityArgs] = None
    labels_presence: Optional[LabelsPresence] = None


@dataclass
class PriorityPolicy:
    name: str
    weight: int = 1
    # arguments (api/types.go:95-123)
    service_antiaffinity_label: Optional[str] = None
    label_preference: Optional[Dict] = None


@dataclass
class ExtenderConfig:
    """api/types.go:129-155."""

    url_prefix: str
    filter_verb: str = ""
    prioritize_verb: str = ""
    bind_verb: str = ""
    weight: int = 1
    enable_https: bool = False
    http_timeout_s: float = 5.0  # DefaultExtenderTimeout (extender.go:36)
    node_cache_capable: bool = False


@dataclass
class Policy:
    predicates: Optional[List[PredicatePolicy]] = None
    priorities: Optional[List[PriorityPolicy]] = None
    extenders: List[ExtenderConfig] = field(default_factory=list)


def parse_policy(text: str) -> Policy:
    """Parse the reference's Policy JSON (same field names; apiVersion/kind
    tolerated and ignored, like the lenient codec the reference uses)."""
    obj = json.loads(text)
    predicates = None
    if "predicates" in obj and obj["predicates"] is not None:
        predicates = []
        for p in obj["predicates"]:
            arg = p.get("argument") or {}
            sa = arg.get("serviceAffinity")
            lp = arg.get("labelsPresence")
            predicates.append(PredicatePolicy(
                name=p["name"],
                service_affinity=ServiceAffinityArgs(sa.get("labels") or [])
                if sa else None,
                labels_presence=LabelsPresence(lp.get("labels") or [],
                                               bool(lp.get("presence", True)))
                if lp else None,
            ))
    priorities = None
    if "priorities" in obj and obj["priorities"] is not None:
        priorities = []
        for p in obj["priorities"]:
            arg = p.get("argument") or {}
            saa = arg.get("serviceAntiAffinity")
            priorities.append(PriorityPolicy(
                name=p["name"],
                weight=int(p.get("weight", 1)),
                service_antiaffinity_label=(saa or {}).get("label"),
                label_preference=arg.get("labelPreference"),
            ))
    extenders = []
    for e in obj.get("extenders") or []:
        timeout = e.get("httpTimeout")
        if isinstance(timeout, (int, float)):
            timeout = timeout / 1e9  # Go time.Duration marshals as int ns
        extenders.append(ExtenderConfig(
            url_prefix=e.get("urlPrefix", ""),
            filter_verb=e.get("filterVerb", ""),
            prioritize_verb=e.get("prioritizeVerb", ""),
            bind_verb=e.get("bindVerb", ""),
            weight=int(e.get("weight", 1)),
            enable_https=bool(e.get("enableHttps", False)),
            http_timeout_s=float(timeout) if timeout else 5.0,
            node_cache_capable=bool(e.get("nodeCacheCapable", False)),
        ))
    return Policy(predicates=predicates, priorities=priorities,
                  extenders=extenders)


# ---------------------------------------------------------------------------
# algorithm providers (defaults.go)
# ---------------------------------------------------------------------------

# defaults.go:118 defaultPredicates — names kept verbatim so policy files and
# provider selection stay drop-in compatible. Kernel coverage status lives in
# the engine's predicate registry; unimplemented ones map to the host oracle
# or are pending (volumes).
DEFAULT_PREDICATES = [
    "NoVolumeZoneConflict", "MaxEBSVolumeCount", "MaxGCEPDVolumeCount",
    "MaxAzureDiskVolumeCount", "MatchInterPodAffinity", "NoDiskConflict",
    "GeneralPredicates", "PodToleratesNodeTaints", "CheckNodeMemoryPressure",
    "CheckNodeDiskPressure", "CheckNodeCondition", "NoVolumeNodeConflict",
]

# defaults.go:191 defaultPriorities with weights
DEFAULT_PRIORITIES_POLICY: List[Tuple[str, int]] = [
    ("SelectorSpreadPriority", 1),
    ("InterPodAffinityPriority", 1),
    ("LeastRequestedPriority", 1),
    ("BalancedResourceAllocation", 1),
    ("NodePreferAvoidPodsPriority", 10000),
    ("NodeAffinityPriority", 1),
    ("TaintTolerationPriority", 1),
]

PROVIDERS: Dict[str, Dict] = {
    "DefaultProvider": {
        "predicates": list(DEFAULT_PREDICATES),
        "priorities": list(DEFAULT_PRIORITIES_POLICY),
    },
    "ClusterAutoscalerProvider": {
        "predicates": list(DEFAULT_PREDICATES),
        "priorities": [("MostRequestedPriority", 1) if n == "LeastRequestedPriority"
                       else (n, w) for n, w in DEFAULT_PRIORITIES_POLICY],
    },
}


def provider_priorities(name: str = "DefaultProvider",
                        implemented: Optional[List[str]] = None
                        ) -> Tuple[Tuple[str, int], ...]:
    """Priority tuple for an algorithm provider, filtered to kernels that
    exist when `implemented` is given."""
    pairs = PROVIDERS[name]["priorities"]
    if implemented is not None:
        pairs = [(n, w) for n, w in pairs if n in implemented]
    return tuple(pairs)
