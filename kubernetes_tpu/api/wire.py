"""Generic wire codec: any API dataclass <-> JSON-able dict with a `kind`.

The reference's wire format is the versioned k8s API (JSON/protobuf via
runtime.Scheme + generated conversions — staging/src/k8s.io/apimachinery/pkg/
runtime). Here the object model is plain dataclasses, so the scheme is
reflection: dataclass fields encode under their own names, nested dataclasses
/ enums / lists / dicts recurse, and a `kind` discriminator selects the
constructor on decode. Pod/Node additionally accept the upstream k8s
manifest shape (metadata/spec/status) through api/serde.py — `decode_any`
sniffs which of the two encodings it was handed, so `ktctl create -f` takes
real kubectl manifests for the core kinds.
"""

from __future__ import annotations

import dataclasses
import enum
import json
from typing import Any, Dict, Optional, Type

from kubernetes_tpu.api import cluster as cluster_mod
from kubernetes_tpu.api import rbac as rbac_mod
from kubernetes_tpu.api import types as core
from kubernetes_tpu.api import workloads as wl
from kubernetes_tpu.api.serde import decode_node, decode_pod

KIND_REGISTRY: Dict[str, Type] = {
    "Pod": core.Pod,
    "Node": core.Node,
    "PersistentVolume": core.PersistentVolume,
    "PersistentVolumeClaim": core.PersistentVolumeClaim,
    "Binding": core.Binding,
    "Event": core.Event,
    "ReplicaSet": wl.ReplicaSet,
    "ReplicationController": wl.ReplicationController,
    "Deployment": wl.Deployment,
    "Job": wl.Job,
    "CronJob": getattr(wl, "CronJob", None),
    "DaemonSet": wl.DaemonSet,
    "StatefulSet": wl.StatefulSet,
    "Namespace": wl.Namespace,
    "Service": wl.Service,
    "Endpoints": wl.Endpoints,
    "PriorityClass": wl.PriorityClass,
    "ResourceQuota": cluster_mod.ResourceQuota,
    "LimitRange": cluster_mod.LimitRange,
    "ServiceAccount": cluster_mod.ServiceAccount,
    "Secret": cluster_mod.Secret,
    "ConfigMap": cluster_mod.ConfigMap,
    "PodDisruptionBudget": cluster_mod.PodDisruptionBudget,
    "CertificateSigningRequest": cluster_mod.CertificateSigningRequest,
    "StorageClass": cluster_mod.StorageClass,
    "HorizontalPodAutoscaler": wl.HorizontalPodAutoscaler,
    "Role": rbac_mod.Role,
    "ClusterRole": rbac_mod.ClusterRole,
    "RoleBinding": rbac_mod.RoleBinding,
    "ClusterRoleBinding": rbac_mod.ClusterRoleBinding,
}


def _psp_type():
    from kubernetes_tpu.security.psp import PodSecurityPolicy
    return PodSecurityPolicy


def _ext_types():
    from kubernetes_tpu.api import extensions as ext
    return ext


KIND_REGISTRY["PodSecurityPolicy"] = _psp_type()
KIND_REGISTRY["CustomResourceDefinition"] = \
    _ext_types().CustomResourceDefinition
KIND_REGISTRY["APIService"] = _ext_types().APIService
KIND_REGISTRY = {k: v for k, v in KIND_REGISTRY.items() if v is not None}


def register_kind(kind: str, cls: Type) -> None:
    """Extension point (the CRD path registers decoded shapes here)."""
    KIND_REGISTRY[kind] = cls


def encode(obj: Any, kind: Optional[str] = None) -> Any:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {f.name: encode(getattr(obj, f.name))
               for f in dataclasses.fields(obj)}
        if kind:
            out["kind"] = kind
        return out
    if isinstance(obj, enum.Enum):
        return obj.value
    if isinstance(obj, dict):
        return {k: encode(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [encode(v) for v in obj]
    return obj


def _decode_value(val: Any, tp: Any) -> Any:
    origin = getattr(tp, "__origin__", None)
    if val is None:
        return None
    if origin is list:
        (item_tp,) = tp.__args__
        return [_decode_value(v, item_tp) for v in val]
    if origin is tuple:
        args = tp.__args__
        if len(args) == 2 and args[1] is Ellipsis:
            return tuple(_decode_value(v, args[0]) for v in val)
        return tuple(_decode_value(v, t) for v, t in zip(val, args))
    if origin is dict:
        _, v_tp = tp.__args__
        return {k: _decode_value(v, v_tp) for k, v in val.items()}
    if origin is not None and str(origin) in ("typing.Union",) or \
            str(tp).startswith("typing.Optional"):
        for arg in tp.__args__:
            if arg is type(None):
                continue
            try:
                return _decode_value(val, arg)
            except (TypeError, ValueError, KeyError):
                continue
        return val
    if isinstance(tp, type) and issubclass(tp, enum.Enum):
        return tp(val)
    if dataclasses.is_dataclass(tp):
        return decode_dataclass(val, tp)
    return val


def _resolve_hints(cls: Type) -> Dict[str, Any]:
    import typing

    mod = vars(__import__(cls.__module__, fromlist=["_"]))
    return typing.get_type_hints(cls, globalns=mod)


def decode_dataclass(data: Dict[str, Any], cls: Type) -> Any:
    hints = _resolve_hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name in data:
            kwargs[f.name] = _decode_value(data[f.name], hints.get(f.name))
    return cls(**kwargs)


def decode_any(data: Dict[str, Any], kind: Optional[str] = None) -> Any:
    """Decode a wire dict. Accepts both the native encoding and (for
    Pod/Node) upstream k8s manifests — sniffed by the metadata/spec shape."""
    kind = kind or data.get("kind", "")
    if not kind:
        raise ValueError("object has no kind")
    if "metadata" in data and kind == "Pod":
        return decode_pod(data)
    if "metadata" in data and kind == "Node":
        return decode_node(data)
    if "metadata" in data and kind == "CustomResourceDefinition":
        return decode_crd_manifest(data)
    cls = KIND_REGISTRY.get(kind)
    if cls is None:
        # custom (CRD-defined) kind: decode into the schemaless
        # CustomResource shape — both the native flat encoding and the
        # upstream metadata/spec manifest shape are accepted
        from kubernetes_tpu.api.extensions import CustomResource
        if "metadata" in data:
            meta = data.get("metadata", {})
            return CustomResource(
                kind=kind, name=meta.get("name", ""),
                namespace=meta.get("namespace", ""),
                api_version=data.get("apiVersion", ""),
                labels=dict(meta.get("labels", {})),
                spec=dict(data.get("spec", {})),
                status=dict(data.get("status", {})))
        body = {k: v for k, v in data.items()
                if k not in ("kind", "apiVersion")}
        return decode_dataclass({"kind": kind, **body}, CustomResource)
    data = {k: v for k, v in data.items() if k not in ("kind", "apiVersion")}
    return decode_dataclass(data, cls)


def decode_crd_manifest(data: Dict[str, Any]) -> Any:
    """Decode an upstream apiextensions.k8s.io CRD manifest
    (metadata/spec shape, incl. the v1.7-era
    spec.validation.openAPIV3Schema) into the native
    CustomResourceDefinition."""
    from kubernetes_tpu.api.extensions import CRDNames, \
        CustomResourceDefinition
    meta, spec = data.get("metadata", {}), data.get("spec", {})
    names = spec.get("names", {})
    validation: Dict[str, Any] = {}
    schema = (spec.get("validation", {}) or {}).get("openAPIV3Schema", {})
    spec_schema = (schema.get("properties", {}) or {}).get("spec", {})
    if spec_schema:
        validation = dict(spec_schema.get("properties", {}) or {})
        if spec_schema.get("required"):
            validation["required"] = list(spec_schema["required"])
    return CustomResourceDefinition(
        name=meta.get("name", ""),
        group=spec.get("group", ""),
        version=spec.get("version", ""),
        names=CRDNames(
            plural=names.get("plural", ""),
            kind=names.get("kind", ""),
            singular=names.get("singular", ""),
            short_names=list(names.get("shortNames", []))),
        scope=spec.get("scope", "Namespaced"),
        validation=validation)


def dumps(obj: Any, kind: str) -> str:
    return json.dumps(encode(obj, kind=kind))


def loads(text: str) -> Any:
    return decode_any(json.loads(text))
