"""runtime.Scheme analog: versioned <-> internal conversion + defaulting.

The reference's API machinery keeps two type families per group —
versioned external types (staging/src/k8s.io/api/...) and internal hub
types (pkg/apis/...) — with generated conversion + defaulting walked
through runtime.Scheme (staging/src/k8s.io/apimachinery/pkg/runtime/
scheme.go: AddKnownTypes, AddConversionFuncs, Default, Convert). Wire
payloads always carry a versioned shape + apiVersion; everything above
the codec layer speaks internal.

This module is that machinery at the scale this framework needs:
a Scheme with per-(group/version, kind) codecs, each owning decode
(versioned JSON dict -> internal dataclass, defaults applied) and
encode (internal -> versioned dict). Implemented groups:

- componentconfig/v1alpha1 KubeSchedulerConfiguration
  (pkg/apis/componentconfig/types.go:158-198 + v1alpha1 defaults in
  pkg/apis/componentconfig/v1alpha1/defaults.go: scheduler name,
  hard-pod-affinity weight, leader-election timings).
- scheduler Policy v1 (plugin/pkg/scheduler/api/v1/types.go — the
  versioned mirror of api/types.go, decoded through api/policy.py).

The invariant tests pin: decode(encode(x)) == x (round-trip through the
versioned form), unknown apiVersion/kind fail loudly, and defaulting
happens exactly once, at decode (scheme.Default semantics).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

# ------------------------------------------------------- internal types


@dataclass
class LeaderElectionConfiguration:
    """componentconfig.LeaderElectionConfiguration (types.go:76-105)."""

    leader_elect: bool = True
    lease_duration_s: float = 15.0
    renew_deadline_s: float = 10.0
    retry_period_s: float = 2.0
    lock_object_namespace: str = "kube-system"
    lock_object_name: str = "kube-scheduler"


@dataclass
class KubeSchedulerConfiguration:
    """Internal componentconfig.KubeSchedulerConfiguration
    (pkg/apis/componentconfig/types.go:158-198), the subset this
    framework's daemon consumes."""

    scheduler_name: str = "default-scheduler"
    algorithm_provider: str = "DefaultProvider"
    policy_config_file: str = ""
    policy_configmap: str = ""
    policy_configmap_namespace: str = "kube-system"
    use_legacy_policy_config: bool = False
    healthz_bind_address: str = "0.0.0.0:10251"
    enable_profiling: bool = True
    enable_contention_profiling: bool = False
    hard_pod_affinity_symmetric_weight: int = 1
    failure_domains: str = \
        "kubernetes.io/hostname,failure-domain.beta.kubernetes.io/zone," \
        "failure-domain.beta.kubernetes.io/region"
    leader_election: LeaderElectionConfiguration = field(
        default_factory=LeaderElectionConfiguration)
    feature_gates: Dict[str, bool] = field(default_factory=dict)


# ---------------------------------------------------------------- scheme


class SchemeError(Exception):
    pass


class Scheme:
    """AddKnownTypes + Convert, dict-backed: (apiVersion, kind) -> codec."""

    def __init__(self):
        self._codecs: Dict[Tuple[str, str], Tuple[
            Callable[[Dict[str, Any]], Any],
            Callable[[Any], Dict[str, Any]]]] = {}

    def register(self, api_version: str, kind: str,
                 decode: Callable[[Dict[str, Any]], Any],
                 encode: Callable[[Any], Dict[str, Any]]) -> None:
        self._codecs[(api_version, kind)] = (decode, encode)

    def versions(self) -> Tuple[Tuple[str, str], ...]:
        return tuple(sorted(self._codecs))

    def decode(self, data: Dict[str, Any]) -> Any:
        """Versioned wire dict -> internal object, defaults applied —
        the codec DecoderToVersion path."""
        gv = data.get("apiVersion", "")
        kind = data.get("kind", "")
        codec = self._codecs.get((gv, kind))
        if codec is None:
            raise SchemeError(
                f"no kind {kind!r} registered for version {gv!r}")
        return codec[0](data)

    def encode(self, obj: Any, api_version: str,
               kind: str) -> Dict[str, Any]:
        codec = self._codecs.get((api_version, kind))
        if codec is None:
            raise SchemeError(
                f"no kind {kind!r} registered for version {api_version!r}")
        out = codec[1](obj)
        out["apiVersion"] = api_version
        out["kind"] = kind
        return out

    def convert(self, data: Dict[str, Any], to_version: str) -> \
            Dict[str, Any]:
        """Versioned -> versioned through the internal hub (the two-hop
        conversion runtime.Scheme always performs)."""
        obj = self.decode(data)
        return self.encode(obj, to_version, data.get("kind", ""))


# ------------------------------- componentconfig/v1alpha1 codec functions


_SCHED_GV = "componentconfig/v1alpha1"
_SCHED_KIND = "KubeSchedulerConfiguration"


def _decode_scheduler_config(data: Dict[str, Any]) -> \
        KubeSchedulerConfiguration:
    """v1alpha1 camelCase wire -> internal, with the defaults of
    pkg/apis/componentconfig/v1alpha1/defaults.go applied for absent
    fields (SetDefaults_KubeSchedulerConfiguration)."""
    le_raw = data.get("leaderElection", {}) or {}
    le = LeaderElectionConfiguration(
        leader_elect=le_raw.get("leaderElect", True),
        lease_duration_s=_seconds(le_raw.get("leaseDuration", "15s")),
        renew_deadline_s=_seconds(le_raw.get("renewDeadline", "10s")),
        retry_period_s=_seconds(le_raw.get("retryPeriod", "2s")),
        lock_object_namespace=le_raw.get("lockObjectNamespace",
                                         "kube-system"),
        lock_object_name=le_raw.get("lockObjectName", "kube-scheduler"))
    weight = data.get("hardPodAffinitySymmetricWeight", 1)
    if not 0 <= weight <= 100:
        raise SchemeError(
            f"hardPodAffinitySymmetricWeight must be in [0, 100], "
            f"got {weight}")  # validation.go ValidateKubeSchedulerConfiguration
    gates = {}
    for part in filter(None, str(data.get("featureGates", "")).split(",")):
        k, _, v = part.partition("=")
        gates[k.strip()] = v.strip().lower() == "true"
    return KubeSchedulerConfiguration(
        scheduler_name=data.get("schedulerName", "default-scheduler"),
        algorithm_provider=data.get("algorithmProvider", "DefaultProvider"),
        policy_config_file=data.get("policyConfigFile", ""),
        policy_configmap=data.get("policyConfigMapName", ""),
        policy_configmap_namespace=data.get("policyConfigMapNamespace",
                                            "kube-system"),
        use_legacy_policy_config=data.get("useLegacyPolicyConfig", False),
        healthz_bind_address=data.get("healthzBindAddress", "0.0.0.0:10251"),
        enable_profiling=data.get("enableProfiling", True),
        enable_contention_profiling=data.get("enableContentionProfiling",
                                             False),
        hard_pod_affinity_symmetric_weight=weight,
        failure_domains=data.get(
            "failureDomains",
            KubeSchedulerConfiguration.failure_domains),
        leader_election=le,
        feature_gates=gates)


def _encode_scheduler_config(cfg: KubeSchedulerConfiguration) -> \
        Dict[str, Any]:
    return {
        "schedulerName": cfg.scheduler_name,
        "algorithmProvider": cfg.algorithm_provider,
        "policyConfigFile": cfg.policy_config_file,
        "policyConfigMapName": cfg.policy_configmap,
        "policyConfigMapNamespace": cfg.policy_configmap_namespace,
        "useLegacyPolicyConfig": cfg.use_legacy_policy_config,
        "healthzBindAddress": cfg.healthz_bind_address,
        "enableProfiling": cfg.enable_profiling,
        "enableContentionProfiling": cfg.enable_contention_profiling,
        "hardPodAffinitySymmetricWeight":
            cfg.hard_pod_affinity_symmetric_weight,
        "failureDomains": cfg.failure_domains,
        "leaderElection": {
            "leaderElect": cfg.leader_election.leader_elect,
            "leaseDuration": f"{cfg.leader_election.lease_duration_s:g}s",
            "renewDeadline": f"{cfg.leader_election.renew_deadline_s:g}s",
            "retryPeriod": f"{cfg.leader_election.retry_period_s:g}s",
            "lockObjectNamespace":
                cfg.leader_election.lock_object_namespace,
            "lockObjectName": cfg.leader_election.lock_object_name,
        },
        "featureGates": ",".join(
            f"{k}={'true' if v else 'false'}"
            for k, v in sorted(cfg.feature_gates.items())),
    }


def _seconds(s: Any) -> float:
    """metav1.Duration strings ("15s", "1m30s") or bare numbers."""
    if isinstance(s, (int, float)):
        return float(s)
    total = 0.0
    num = ""
    units = {"h": 3600.0, "m": 60.0, "s": 1.0, "ms": 0.001}
    i = 0
    text = str(s)
    while i < len(text):
        ch = text[i]
        if ch.isdigit() or ch == ".":
            num += ch
            i += 1
            continue
        unit = ch
        if text[i:i + 2] == "ms":
            unit = "ms"
            i += 1
        if unit not in units or not num:
            raise SchemeError(f"invalid duration {s!r}")
        try:
            value = float(num)
        except ValueError:
            raise SchemeError(f"invalid duration {s!r}") from None
        total += value * units[unit]
        num = ""
        i += 1
    if num:
        raise SchemeError(f"invalid duration {s!r} (missing unit)")
    return total


# --------------------------------------------------- scheduler Policy v1


def _decode_policy_v1(data: Dict[str, Any]):
    """Policy v1 (plugin/pkg/scheduler/api/v1/types.go) decoded through
    the existing parser — same wire shape, the version label is what the
    scheme dispatches on (v1 and internal are field-identical in 1.7)."""
    import json as _json

    from kubernetes_tpu.api.policy import parse_policy
    return parse_policy(_json.dumps(data))


def _encode_policy_v1(policy) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    if policy.predicates is not None:
        out["predicates"] = [
            {"name": p.name, **({"argument": p.argument_raw}
                                if getattr(p, "argument_raw", None) else {})}
            for p in policy.predicates]
    if policy.priorities is not None:
        out["priorities"] = [
            {"name": p.name, "weight": p.weight,
             **({"argument": p.argument_raw}
                if getattr(p, "argument_raw", None) else {})}
            for p in policy.priorities]
    if policy.extenders:
        out["extenders"] = [
            {"urlPrefix": e.url_prefix, "filterVerb": e.filter_verb,
             "prioritizeVerb": e.prioritize_verb, "bindVerb": e.bind_verb,
             "weight": e.weight, "nodeCacheCapable": e.node_cache_capable}
            for e in policy.extenders]
    return out


# ------------------------------------------------- core group (Pod/Node)
#
# The defining apimachinery axis (pkg/api/v1/conversion.go + runtime.Scheme
# Convert): versioned external shapes <-> the internal dataclasses, with
# defaulting applied exactly once at decode. v1 is the serde wire shape
# (metadata/spec, camelCase). "v2" is a hypothetical next version proving
# the conversion machinery handles FIELD RENAMES through the internal hub:
#   spec.nodeName      -> spec.boundNode
#   spec.schedulerName -> spec.scheduler
#   (Node) spec.unschedulable -> spec.schedulingDisabled
# Converting v1<->v2 is always two hops through internal, never
# field-by-field between versions — exactly runtime.Scheme's shape.


def _decode_pod_v1(data: Dict[str, Any]):
    from kubernetes_tpu.api import serde
    return serde.decode_pod(data)


def _encode_pod_v1(pod) -> Dict[str, Any]:
    from kubernetes_tpu.api import serde
    return serde.encode_pod(pod)


def _decode_pod_v2(data: Dict[str, Any]):
    from kubernetes_tpu.api import serde
    spec = dict(data.get("spec") or {})
    if "boundNode" in spec:
        spec["nodeName"] = spec.pop("boundNode")
    if "scheduler" in spec:
        spec["schedulerName"] = spec.pop("scheduler")
    return serde.decode_pod({**data, "spec": spec})


def _encode_pod_v2(pod) -> Dict[str, Any]:
    from kubernetes_tpu.api import serde
    out = serde.encode_pod(pod)
    spec = out["spec"]
    spec["boundNode"] = spec.pop("nodeName")
    spec["scheduler"] = spec.pop("schedulerName")
    return out


def _decode_node_v1(data: Dict[str, Any]):
    from kubernetes_tpu.api import serde
    return serde.decode_node(data)


def _encode_node_v1(node) -> Dict[str, Any]:
    from kubernetes_tpu.api import serde
    return serde.encode_node(node)


def _decode_node_v2(data: Dict[str, Any]):
    spec = dict(data.get("spec") or {})
    if "schedulingDisabled" in spec:
        spec["unschedulable"] = spec.pop("schedulingDisabled")
    return _decode_node_v1({**data, "spec": spec})


def _encode_node_v2(node) -> Dict[str, Any]:
    out = _encode_node_v1(node)
    spec = out["spec"]
    spec["schedulingDisabled"] = spec.pop("unschedulable")
    return out


def _decode_service_v1(data: Dict[str, Any]):
    from kubernetes_tpu.api import wire
    if "metadata" in data:
        # the kubectl manifest shape: flatten metadata + spec into the
        # native field namespace before the reflective decode
        meta = data.get("metadata") or {}
        spec = data.get("spec") or {}
        body = {**spec,
                "name": meta.get("name", ""),
                "namespace": meta.get("namespace", "default"),
                "labels": dict(meta.get("labels") or {}),
                "annotations": dict(meta.get("annotations") or {})}
    else:
        body = {k: v for k, v in data.items()
                if k not in ("apiVersion",)}
    return wire.decode_any(body, "Service")


def _encode_service_v1(svc) -> Dict[str, Any]:
    from kubernetes_tpu.api import wire
    return wire.encode(svc, "Service")


def _generic_codec(kind: str):
    """v1 codec for a reflective wire kind: accepts both the flat native
    encoding and the kubectl metadata/spec manifest shape (flattened the
    way _decode_service_v1 does), encodes flat."""
    # kinds wire.decode_any sniffs the metadata/spec shape for itself —
    # flattening first would bypass their dedicated manifest decoders
    # (e.g. decode_crd_manifest's shortNames + openAPIV3Schema handling)
    _SNIFFED = ("Pod", "Node", "CustomResourceDefinition")

    def decode(data: Dict[str, Any]):
        from kubernetes_tpu.api import wire
        if "metadata" in data and kind not in _SNIFFED:
            meta = data.get("metadata") or {}
            spec = data.get("spec") or {}
            body = {**spec,
                    "name": meta.get("name", ""),
                    "namespace": meta.get("namespace", "default"),
                    "labels": dict(meta.get("labels") or {}),
                    "annotations": dict(meta.get("annotations") or {})}
        else:
            body = {k: v for k, v in data.items() if k != "apiVersion"}
        return wire.decode_any(body, kind)

    def encode(obj) -> Dict[str, Any]:
        from kubernetes_tpu.api import wire
        return wire.encode(obj, kind)

    return decode, encode


def default_scheme() -> Scheme:
    from kubernetes_tpu.api.wire import KIND_REGISTRY
    s = Scheme()
    s.register(_SCHED_GV, _SCHED_KIND,
               _decode_scheduler_config, _encode_scheduler_config)
    s.register("v1", "Policy", _decode_policy_v1, _encode_policy_v1)
    # the unversioned legacy Policy files (--use-legacy-policy-config)
    # decode through the same codec
    s.register("", "Policy", _decode_policy_v1, _encode_policy_v1)
    # every reflective wire kind gets a generic v1 codec, so the scheme
    # covers the full served surface (the reference registers every group
    # in its Scheme); the hand-written core codecs below override the
    # kinds with richer semantics
    for kind in KIND_REGISTRY:
        dec, enc = _generic_codec(kind)
        s.register("v1", kind, dec, enc)
    # core group: two served versions over one internal hub
    s.register("v1", "Pod", _decode_pod_v1, _encode_pod_v1)
    s.register("v2", "Pod", _decode_pod_v2, _encode_pod_v2)
    s.register("v1", "Node", _decode_node_v1, _encode_node_v1)
    s.register("v2", "Node", _decode_node_v2, _encode_node_v2)
    s.register("v1", "Service", _decode_service_v1, _encode_service_v1)
    return s


DEFAULT_SCHEME = default_scheme()
