"""API-extension object model: CustomResourceDefinitions + APIServices.

TPU-native analog of the two "extension" staging servers in the reference:

- apiextensions-apiserver (staging/src/k8s.io/apiextensions-apiserver/):
  CustomResourceDefinition lets a user add a new served resource at
  runtime.  The reference validates the CRD (names must be
  ``<plural>.<group>``), accepts or rejects the names against other
  served resources (NamesAccepted condition), then marks the CRD
  Established, at which point a dynamic registry serves CRUD for the
  new kind (apiextensions-apiserver/pkg/apiserver/customresource_handler.go).
- kube-aggregator (staging/src/k8s.io/kube-aggregator/): APIService
  objects map a group/version onto either the local server or a remote
  extension apiserver, with an availability controller probing the
  backend and gating traffic (kube-aggregator/pkg/controllers/status/
  available_controller.go).

The schema subset here mirrors the v1.7-era CRD validation precursor:
per-field type / required / minimum / maximum / enum checks over spec,
enough to exercise the reject-on-invalid path the reference's
apiextensions validation provides.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class CRDNames:
    """CustomResourceDefinitionNames (apiextensions types.go)."""

    plural: str
    kind: str
    singular: str = ""
    short_names: List[str] = field(default_factory=list)
    list_kind: str = ""

    def __post_init__(self) -> None:
        if not self.singular:
            self.singular = self.kind.lower()
        if not self.list_kind:
            self.list_kind = self.kind + "List"


@dataclass
class CRDCondition:
    """Established / NamesAccepted / Terminating condition."""

    type: str
    status: str  # "True" | "False"
    reason: str = ""
    message: str = ""


@dataclass
class CustomResourceDefinition:
    """apiextensions-apiserver CustomResourceDefinition (cluster-scoped).

    ``name`` must equal ``<names.plural>.<group>`` — the same structural
    rule the reference enforces in validation
    (apiextensions-apiserver/pkg/apis/apiextensions/validation/validation.go).
    ``validation`` is a flat field-schema map over ``spec``:
    ``{"replicas": {"type": "integer", "minimum": 0}, ...}`` plus an
    optional ``"required": [...]`` list.
    """

    name: str
    group: str
    version: str
    names: CRDNames
    scope: str = "Namespaced"  # or "Cluster"
    validation: Dict[str, Any] = field(default_factory=dict)
    conditions: List[CRDCondition] = field(default_factory=list)
    # finalizer analog: customresourcecleanup.apiextensions.k8s.io —
    # instances are purged before the definition row disappears
    finalizers: List[str] = field(
        default_factory=lambda: ["customresourcecleanup"])
    terminating: bool = False
    resource_version: int = 0
    namespace: str = ""  # cluster-scoped; kept for store uniformity

    def condition(self, ctype: str) -> Optional[CRDCondition]:
        for c in self.conditions:
            if c.type == ctype:
                return c
        return None

    def set_condition(self, ctype: str, status: str, reason: str = "",
                      message: str = "") -> None:
        c = self.condition(ctype)
        if c is None:
            self.conditions.append(
                CRDCondition(ctype, status, reason, message))
        else:
            c.status, c.reason, c.message = status, reason, message

    @property
    def established(self) -> bool:
        c = self.condition("Established")
        return c is not None and c.status == "True"

    @property
    def names_accepted(self) -> bool:
        c = self.condition("NamesAccepted")
        return c is not None and c.status == "True"


@dataclass
class CustomResource:
    """An instance of a CRD-defined kind — schemaless bag with the same
    metadata shape as every built-in object, so the generic store, watch
    log, and WAL handle it unmodified (the dynamic-registry property of
    customresource_handler.go)."""

    kind: str
    name: str
    namespace: str = ""
    api_version: str = ""  # "<group>/<version>"
    labels: Dict[str, str] = field(default_factory=dict)
    spec: Dict[str, Any] = field(default_factory=dict)
    status: Dict[str, Any] = field(default_factory=dict)
    resource_version: int = 0


@dataclass
class ServiceReference:
    """Backend of an aggregated API (kube-aggregator types.go)."""

    namespace: str
    name: str


@dataclass
class APIService:
    """kube-aggregator APIService: routes <version>.<group> either to the
    local server (service=None) or to an extension apiserver."""

    name: str  # "<version>.<group>"
    group: str
    version: str
    service: Optional[ServiceReference] = None
    group_priority_minimum: int = 1000
    version_priority: int = 100
    available: bool = False
    available_message: str = ""
    resource_version: int = 0
    namespace: str = ""

    @property
    def local(self) -> bool:
        return self.service is None


class SchemaError(Exception):
    """Custom object rejected by the CRD's validation schema."""


def validate_custom(crd: CustomResourceDefinition, obj: CustomResource) -> None:
    """Enforce the CRD's flat spec schema. Mirrors what apiextensions
    validation rejects: wrong primitive type, out-of-range numerics,
    values outside an enum, and missing required fields."""
    schema = crd.validation or {}
    required = schema.get("required", [])
    for req in required:
        if req not in obj.spec:
            raise SchemaError(f"spec.{req} is required")
    _TYPES = {
        "integer": (int,),
        "number": (int, float),
        "string": (str,),
        "boolean": (bool,),
        "array": (list,),
        "object": (dict,),
    }
    for fname, fschema in schema.items():
        if fname == "required" or fname not in obj.spec:
            continue
        val = obj.spec[fname]
        want = fschema.get("type")
        if want is not None:
            pytypes = _TYPES.get(want)
            if pytypes is None:
                raise SchemaError(f"unknown schema type {want!r}")
            # bool is an int subclass in Python; keep integer strict
            if want in ("integer", "number") and isinstance(val, bool):
                raise SchemaError(
                    f"spec.{fname}: expected {want}, got boolean")
            if not isinstance(val, pytypes):
                raise SchemaError(
                    f"spec.{fname}: expected {want}, "
                    f"got {type(val).__name__}")
        if ("minimum" in fschema or "maximum" in fschema) and (
                isinstance(val, bool) or not isinstance(val, (int, float))):
            # bounds imply a numeric field even when "type" was omitted;
            # a non-numeric value must 422, not TypeError into a 500
            raise SchemaError(
                f"spec.{fname}: expected a number for a bounded field, "
                f"got {type(val).__name__}")
        if "minimum" in fschema and val < fschema["minimum"]:
            raise SchemaError(
                f"spec.{fname}: {val} is less than minimum "
                f"{fschema['minimum']}")
        if "maximum" in fschema and val > fschema["maximum"]:
            raise SchemaError(
                f"spec.{fname}: {val} is greater than maximum "
                f"{fschema['maximum']}")
        if "enum" in fschema and val not in fschema["enum"]:
            raise SchemaError(
                f"spec.{fname}: {val!r} not in enum {fschema['enum']}")
