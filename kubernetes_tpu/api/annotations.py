"""Scheduler-read annotation parsers shared by the tensorization and oracle
paths (one implementation so kernel and host semantics cannot diverge)."""

from __future__ import annotations

import json
from typing import Dict, List, Tuple

# reference: v1.PreferAvoidPodsAnnotationKey, read by
# pkg/api/v1/helper GetAvoidPodsFromNodeAnnotations
# (node_prefer_avoid_pods.go:48-58)
AVOID_PODS_ANNOTATION = "scheduler.alpha.kubernetes.io/preferAvoidPods"


def parse_avoid_annotation(annotations: Dict[str, str]) -> List[Tuple[str, str]]:
    """-> [(controller kind, controller uid)] from the preferAvoidPods node
    annotation. The Go reference unmarshals into a typed struct, so any
    shape mismatch (non-object JSON, non-object list entries) degrades to
    'no avoidance' rather than erroring — mirrored here."""
    raw = annotations.get(AVOID_PODS_ANNOTATION)
    if not raw:
        return []
    try:
        avoids = json.loads(raw)
    except ValueError:
        return []
    if not isinstance(avoids, dict):
        return []
    entries = avoids.get("preferAvoidPods")
    if not isinstance(entries, list):
        return []
    out: List[Tuple[str, str]] = []
    for avoid in entries:
        if not isinstance(avoid, dict):
            continue
        sig = avoid.get("podSignature")
        ctrl = sig.get("podController") if isinstance(sig, dict) else None
        if isinstance(ctrl, dict) and ctrl.get("kind") and ctrl.get("uid"):
            out.append((str(ctrl["kind"]), str(ctrl["uid"])))
    return out
