"""Generated-protobuf loader: imports ktpb_pb2, generating it with protoc
on demand (mirroring the native-lib build-on-demand pattern). Returns None
when neither a generated module nor protoc is available — callers fall
back to the JSON path."""

from __future__ import annotations

import os
import subprocess
import threading
from kubernetes_tpu.analysis import lockcheck

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(os.path.dirname(_HERE))
_PROTO_DIR = os.path.join(os.path.dirname(_ROOT), "proto")
_GEN = os.path.join(_HERE, "ktpb_pb2.py")

_lock = lockcheck.make_lock("api.pb._lock")
_mod = None
_tried = False


def load():
    """The ktpb_pb2 module, or None."""
    global _mod, _tried
    if _mod is not None or _tried:
        return _mod
    with _lock:
        if _mod is not None or _tried:
            return _mod
        _tried = True
        if not os.path.exists(_GEN):
            src = os.path.join(_PROTO_DIR, "ktpb.proto")
            if os.path.exists(src):
                try:
                    subprocess.run(
                        ["protoc", f"--proto_path={_PROTO_DIR}",
                         f"--python_out={_HERE}", "ktpb.proto"],
                        check=True, capture_output=True, timeout=120)
                except Exception:
                    return None
        if os.path.exists(_GEN):
            try:
                from kubernetes_tpu.api.pb import ktpb_pb2  # noqa: F401
                _mod = ktpb_pb2
            except Exception:
                _mod = None
    return _mod
