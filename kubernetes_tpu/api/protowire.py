"""Binary (protobuf) codec for bulk Node/Pod transfer — the fast path of
the extender's cache sync beside the JSON contract (SURVEY §5.8; the
reference ships protobuf for every API group via generated.proto and
selects it with --kube-api-content-type, cmd/kubemark/hollow-node.go:71).

Conversion covers exactly the scheduling-read field surface (everything
state/snapshot.py and ops/* consume, including the full affinity tree);
status/runtime-only fields stay on the JSON path. The proto definition is
proto/ktpb.proto; kubernetes_tpu/api/pb generates bindings on demand.
"""

from __future__ import annotations

from typing import List, Optional

from kubernetes_tpu.api import pb
from kubernetes_tpu.api.types import (
    Affinity,
    ConditionStatus,
    Container,
    ContainerImage,
    ContainerPort,
    LabelSelector,
    Node,
    NodeAffinity,
    NodeCondition,
    NodeSelectorTerm,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    Resource,
    SelectorOperator,
    SelectorRequirement,
    Taint,
    TaintEffect,
    Toleration,
    TolerationOperator,
    Volume,
    VolumeKind,
)

CONTENT_TYPE = "application/vnd.ktpb.v1+protobuf"


def available() -> bool:
    return pb.load() is not None


# ------------------------------------------------------------------- nodes


def encode_nodes(nodes: List[Node]) -> bytes:
    m = pb.load()
    out = m.NodeList()
    for n in nodes:
        p = out.items.add()
        p.name = n.name
        p.labels.update(n.labels)
        p.annotations.update(n.annotations)
        a = n.allocatable
        p.milli_cpu = a.milli_cpu
        p.memory = a.memory
        p.nvidia_gpu = a.nvidia_gpu
        p.storage_scratch = a.storage_scratch
        p.storage_overlay = a.storage_overlay
        p.extended.update(a.extended)
        p.allowed_pod_number = n.allowed_pod_number
        p.unschedulable = n.unschedulable
        for t in n.taints:
            pt = p.taints.add()
            pt.key = t.key
            pt.value = t.value
            pt.effect = t.effect.value if isinstance(t.effect, TaintEffect) \
                else str(t.effect)
        for c in n.conditions:
            pc = p.conditions.add()
            pc.type = c.type
            pc.status = c.status.value if hasattr(c.status, "value") \
                else str(c.status)
        p.heartbeat = n.heartbeat
        for img in n.images:
            pi = p.images.add()
            pi.names.extend(img.names)
            pi.size_bytes = img.size_bytes
    return out.SerializeToString()


def decode_nodes(data: bytes) -> List[Node]:
    m = pb.load()
    lst = m.NodeList()
    lst.ParseFromString(data)
    out = []
    for p in lst.items:
        node = Node(
            name=p.name,
            labels=dict(p.labels),
            annotations=dict(p.annotations),
            allocatable=Resource(
                milli_cpu=p.milli_cpu, memory=p.memory,
                nvidia_gpu=p.nvidia_gpu,
                storage_scratch=p.storage_scratch,
                storage_overlay=p.storage_overlay,
                extended=dict(p.extended)),
            allowed_pod_number=p.allowed_pod_number,
            unschedulable=p.unschedulable,
            taints=[Taint(t.key, t.value, TaintEffect(t.effect))
                    for t in p.taints],
            conditions=[NodeCondition(c.type, ConditionStatus(c.status))
                        for c in p.conditions],
            heartbeat=p.heartbeat,
            images=[ContainerImage(list(i.names), i.size_bytes)
                    for i in p.images],
        )
        out.append(node)
    return out


# -------------------------------------------------------------------- pods


def _enc_reqs(dst, reqs: List[SelectorRequirement]) -> None:
    for r in reqs:
        pr = dst.add()
        pr.key = r.key
        pr.operator = r.operator.value \
            if isinstance(r.operator, SelectorOperator) else str(r.operator)
        pr.values.extend(r.values)


def _dec_reqs(src) -> List[SelectorRequirement]:
    return [SelectorRequirement(r.key, SelectorOperator(r.operator),
                                list(r.values)) for r in src]


def _enc_pod_term(dst, t: PodAffinityTerm) -> None:
    if t.label_selector is not None:
        dst.has_selector = True
        dst.label_selector.match_labels.update(t.label_selector.match_labels)
        _enc_reqs(dst.label_selector.match_expressions,
                  t.label_selector.match_expressions)
    dst.namespaces.extend(t.namespaces)
    dst.topology_key = t.topology_key


def _dec_pod_term(src) -> PodAffinityTerm:
    sel = None
    if src.has_selector:
        sel = LabelSelector(
            match_labels=dict(src.label_selector.match_labels),
            match_expressions=_dec_reqs(
                src.label_selector.match_expressions))
    return PodAffinityTerm(label_selector=sel,
                           namespaces=list(src.namespaces),
                           topology_key=src.topology_key)


def _enc_pod_affinity(dst, pa: PodAffinity) -> None:
    for t in pa.required_terms:
        _enc_pod_term(dst.required_terms.add(), t)
    for w, t in pa.preferred_terms:
        wt = dst.preferred_terms.add()
        wt.weight = w
        _enc_pod_term(wt.term, t)


def _dec_pod_affinity(src) -> PodAffinity:
    return PodAffinity(
        required_terms=[_dec_pod_term(t) for t in src.required_terms],
        preferred_terms=[(wt.weight, _dec_pod_term(wt.term))
                         for wt in src.preferred_terms])


def _enc_affinity(dst, aff: Affinity) -> None:
    na = aff.node_affinity
    if na is not None:
        dst.has_node_affinity = True
        if na.required_terms is not None:
            dst.node_affinity.has_required = True
            for t in na.required_terms:
                _enc_reqs(dst.node_affinity.required_terms.add()
                          .match_expressions, t.match_expressions)
        for w, t in na.preferred_terms:
            wt = dst.node_affinity.preferred_terms.add()
            wt.weight = w
            _enc_reqs(wt.term.match_expressions, t.match_expressions)
    if aff.pod_affinity is not None:
        dst.has_pod_affinity = True
        _enc_pod_affinity(dst.pod_affinity, aff.pod_affinity)
    if aff.pod_anti_affinity is not None:
        dst.has_pod_anti_affinity = True
        _enc_pod_affinity(dst.pod_anti_affinity, aff.pod_anti_affinity)


def _dec_affinity(src) -> Affinity:
    na = None
    if src.has_node_affinity:
        req = None
        if src.node_affinity.has_required:
            req = [NodeSelectorTerm(_dec_reqs(t.match_expressions))
                   for t in src.node_affinity.required_terms]
        na = NodeAffinity(
            required_terms=req,
            preferred_terms=[
                (wt.weight,
                 NodeSelectorTerm(_dec_reqs(wt.term.match_expressions)))
                for wt in src.node_affinity.preferred_terms])
    return Affinity(
        node_affinity=na,
        pod_affinity=_dec_pod_affinity(src.pod_affinity)
        if src.has_pod_affinity else None,
        pod_anti_affinity=_dec_pod_affinity(src.pod_anti_affinity)
        if src.has_pod_anti_affinity else None)


def encode_pods(pods: List[Pod]) -> bytes:
    m = pb.load()
    out = m.PodList()
    for pod in pods:
        p = out.items.add()
        p.name = pod.name
        p.namespace = pod.namespace
        p.uid = pod.uid
        p.labels.update(pod.labels)
        p.annotations.update(pod.annotations)
        for c in pod.containers:
            pc = p.containers.add()
            pc.name = c.name
            pc.image = c.image
            pc.requests.update(c.requests)
            pc.limits.update(c.limits)
            for port in c.ports:
                pp = pc.ports.add()
                pp.host_port = port.host_port
                pp.container_port = port.container_port
                pp.protocol = port.protocol
        for v in pod.volumes:
            pv = p.volumes.add()
            pv.name = v.name
            pv.kind = v.kind.value if hasattr(v.kind, "value") else str(v.kind)
            pv.volume_id = v.volume_id
            pv.read_only = v.read_only
            pv.monitors.extend(v.monitors)
            pv.pool = v.pool
            pv.image = v.image
        p.node_name = pod.node_name
        p.node_selector.update(pod.node_selector)
        if pod.affinity is not None:
            p.has_affinity = True
            _enc_affinity(p.affinity, pod.affinity)
        for t in pod.tolerations:
            pt = p.tolerations.add()
            pt.key = t.key
            pt.operator = t.operator.value \
                if isinstance(t.operator, TolerationOperator) else str(t.operator)
            pt.value = t.value
            if t.effect is not None:
                pt.effect = t.effect.value \
                    if isinstance(t.effect, TaintEffect) else str(t.effect)
        p.scheduler_name = pod.scheduler_name
        p.priority = pod.priority
        p.phase = pod.phase
        p.owner_kind = pod.owner_kind
        p.owner_name = pod.owner_name
        p.owner_uid = pod.owner_uid
        p.deleted = pod.deleted
    return out.SerializeToString()


def decode_pods(data: bytes) -> List[Pod]:
    m = pb.load()
    lst = m.PodList()
    lst.ParseFromString(data)
    out = []
    for p in lst.items:
        pod = Pod(
            name=p.name,
            namespace=p.namespace,
            uid=p.uid,
            labels=dict(p.labels),
            annotations=dict(p.annotations),
            containers=[Container(
                name=c.name, image=c.image,
                requests=dict(c.requests), limits=dict(c.limits),
                ports=[ContainerPort(pp.host_port, pp.container_port,
                                     pp.protocol) for pp in c.ports])
                for c in p.containers],
            volumes=[Volume(name=v.name, kind=VolumeKind(v.kind),
                            volume_id=v.volume_id, read_only=v.read_only,
                            monitors=list(v.monitors), pool=v.pool,
                            image=v.image) for v in p.volumes],
            node_name=p.node_name,
            node_selector=dict(p.node_selector),
            affinity=_dec_affinity(p.affinity) if p.has_affinity else None,
            tolerations=[Toleration(
                t.key, TolerationOperator(t.operator), t.value,
                TaintEffect(t.effect) if t.effect else None)
                for t in p.tolerations],
            scheduler_name=p.scheduler_name,
            priority=p.priority,
            phase=p.phase or "Pending",
            owner_kind=p.owner_kind,
            owner_name=p.owner_name,
            owner_uid=p.owner_uid,
            deleted=p.deleted,
        )
        out.append(pod)
    return out
