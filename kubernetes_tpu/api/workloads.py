"""Workload API types: the controller-managed objects.

Mirrors the *consumed* slice of the reference's apps/batch/core workload
surface (staging/src/k8s.io/api/{apps,batch}/v1*, pkg/apis/extensions):
ReplicaSet / ReplicationController / Deployment / Job / DaemonSet /
StatefulSet carry a replica goal, a selector, and a pod template; Namespace
and Service/Endpoints carry lifecycle and routing state. Status fields are
the subset controllers actually reconcile on.

Pod templates are prototype `Pod` objects (name empty); controllers stamp
instances with `stamp_pod`, which fills identity + ownerRef — the moral
equivalent of pkg/controller/controller_utils.go GetPodFromTemplate.
"""

from __future__ import annotations

import copy
import dataclasses
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api.types import (
    LabelSelector,
    Pod,
    SelectorRequirement,
    WorkloadObject,
)


def stamp_pod(template: Pod, name: str, namespace: str,
              owner_kind: str, owner_name: str, owner_uid: str = "") -> Pod:
    """Instantiate a pod from a template with identity + controllerRef."""
    pod = copy.deepcopy(template)
    return dataclasses.replace(
        pod, name=name, namespace=namespace, uid=f"{namespace}/{name}",
        owner_kind=owner_kind, owner_name=owner_name,
        owner_uid=owner_uid or f"{owner_kind}/{namespace}/{owner_name}",
        resource_version=0, node_name=pod.node_name, phase="Pending")


@dataclass
class ReplicaSet:
    """apps/v1beta2 ReplicaSet reduced to spec.{replicas,selector,template} +
    reconciled status (pkg/controller/replicaset)."""

    name: str
    namespace: str = "default"
    annotations: Dict[str, str] = field(default_factory=dict)
    labels: Dict[str, str] = field(default_factory=dict)
    replicas: int = 1
    selector: LabelSelector = field(default_factory=LabelSelector)
    template: Pod = field(default_factory=lambda: Pod(name=""))
    owner_kind: str = ""  # set when managed by a Deployment
    owner_name: str = ""
    # status
    observed_replicas: int = 0
    ready_replicas: int = 0
    resource_version: int = 0

    def key(self) -> str:
        return self.namespace + "/" + self.name


@dataclass
class ReplicationController:
    """core/v1 RC: map selector instead of LabelSelector
    (pkg/controller/replication shares ~all logic with replicaset)."""

    name: str
    namespace: str = "default"
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    replicas: int = 1
    selector: Dict[str, str] = field(default_factory=dict)
    template: Pod = field(default_factory=lambda: Pod(name=""))
    observed_replicas: int = 0
    ready_replicas: int = 0
    resource_version: int = 0

    def key(self) -> str:
        return self.namespace + "/" + self.name


@dataclass
class Deployment:
    """apps Deployment: desired state for ReplicaSets
    (pkg/controller/deployment): RollingUpdate via maxSurge/maxUnavailable,
    template-hash child RS naming, revision tracking."""

    name: str
    namespace: str = "default"
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    replicas: int = 1
    selector: LabelSelector = field(default_factory=LabelSelector)
    template: Pod = field(default_factory=lambda: Pod(name=""))
    max_surge: int = 1
    max_unavailable: int = 0
    paused: bool = False
    # status
    revision: int = 0
    updated_replicas: int = 0
    ready_replicas: int = 0
    resource_version: int = 0

    def key(self) -> str:
        return self.namespace + "/" + self.name


@dataclass
class Job:
    """batch/v1 Job (pkg/controller/job): run template pods to completion."""

    name: str
    namespace: str = "default"
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    completions: int = 1
    parallelism: int = 1
    backoff_limit: int = 6
    template: Pod = field(default_factory=lambda: Pod(name="", restart_policy="Never"))
    # status
    active: int = 0
    succeeded: int = 0
    failed: int = 0
    complete: bool = False
    resource_version: int = 0

    def key(self) -> str:
        return self.namespace + "/" + self.name


@dataclass
class CronJob:
    """batch/v2alpha1 CronJob (pkg/controller/cronjob): spawn Jobs on a cron
    schedule. Schedule syntax supported: '@every <seconds>s' and the 5-field
    subset 'M H * * *' / '*/N * * * *' (the cronjob controller's needs)."""

    name: str
    namespace: str = "default"
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    schedule: str = "@every 60s"
    suspend: bool = False
    concurrency_policy: str = "Allow"  # Allow | Forbid | Replace
    job_template: Job = field(default_factory=lambda: Job(name=""))
    successful_jobs_history_limit: int = 3
    failed_jobs_history_limit: int = 1
    # status
    last_schedule_time: float = 0.0
    active_jobs: List[str] = field(default_factory=list)
    resource_version: int = 0

    def key(self) -> str:
        return self.namespace + "/" + self.name


@dataclass
class HorizontalPodAutoscaler:
    """autoscaling/v1 HPA (pkg/controller/podautoscaler): scale a target
    workload by the ratio of observed to target CPU utilization —
    desired = ceil(current * observed/target), bounded to [min,max], with
    the reference's 10% tolerance dead-band (horizontal.go)."""

    name: str
    namespace: str = "default"
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    target_kind: str = "ReplicaSet"
    target_name: str = ""
    min_replicas: int = 1
    max_replicas: int = 10
    target_cpu_utilization: int = 80  # percent of requests
    # status
    current_replicas: int = 0
    desired_replicas: int = 0
    current_cpu_utilization: int = 0
    resource_version: int = 0

    def key(self) -> str:
        return self.namespace + "/" + self.name


@dataclass
class DaemonSet:
    """extensions DaemonSet (pkg/controller/daemon): one pod per eligible
    node; eligibility mirrors the scheduler's GeneralPredicates-lite check
    the daemon controller does itself (daemoncontroller.go nodeShouldRunDaemonPod)."""

    name: str
    namespace: str = "default"
    labels: Dict[str, str] = field(default_factory=dict)
    selector: LabelSelector = field(default_factory=LabelSelector)
    template: Pod = field(default_factory=lambda: Pod(name=""))
    annotations: Dict[str, str] = field(default_factory=dict)
    # status
    desired_scheduled: int = 0
    current_scheduled: int = 0
    resource_version: int = 0

    def key(self) -> str:
        return self.namespace + "/" + self.name


@dataclass
class StatefulSet:
    """apps StatefulSet (pkg/controller/statefulset): ordinal identity pods
    <name>-0..N-1, created in order, scaled down in reverse."""

    name: str
    namespace: str = "default"
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    replicas: int = 1
    selector: LabelSelector = field(default_factory=LabelSelector)
    template: Pod = field(default_factory=lambda: Pod(name=""))
    service_name: str = ""
    # status
    ready_replicas: int = 0
    resource_version: int = 0

    def key(self) -> str:
        return self.namespace + "/" + self.name


@dataclass
class Namespace:
    """core/v1 Namespace with the two-phase delete the namespace lifecycle
    controller drives (pkg/controller/namespace): Active -> Terminating ->
    (contents deleted) -> gone."""

    name: str
    phase: str = "Active"  # Active | Terminating
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    resource_version: int = 0


@dataclass
class ServicePort:
    port: int = 0
    target_port: int = 0
    protocol: str = "TCP"
    node_port: int = 0


@dataclass
class Service:
    """core/v1 Service reduced to what endpoints + proxy consume: the
    selector, ports, and a cluster VIP."""

    name: str
    namespace: str = "default"
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    selector: Dict[str, str] = field(default_factory=dict)
    ports: List[ServicePort] = field(default_factory=list)
    cluster_ip: str = ""
    type: str = "ClusterIP"  # ClusterIP | NodePort | LoadBalancer
    load_balancer_ip: str = ""  # status.loadBalancer ingress (service ctrl)
    resource_version: int = 0

    def key(self) -> str:
        return self.namespace + "/" + self.name


@dataclass
class EndpointAddress:
    pod_key: str = ""
    node_name: str = ""
    ip: str = ""


@dataclass
class Endpoints:
    """core/v1 Endpoints: ready pod addresses behind a service, reconciled by
    the endpoint controller (pkg/controller/endpoint)."""

    name: str
    namespace: str = "default"
    addresses: List[EndpointAddress] = field(default_factory=list)
    resource_version: int = 0

    def key(self) -> str:
        return self.namespace + "/" + self.name


@dataclass
class PriorityClass:
    """scheduling.k8s.io PriorityClass (v1.7 had only the PodPriority gate;
    the class object is the forward-compatible config surface)."""

    name: str
    value: int = 0
    global_default: bool = False
    resource_version: int = 0


def selector_of(obj) -> LabelSelector:
    """Uniform LabelSelector view over RS/Deployment/DS/SS (LabelSelector)
    and RC/Service (map selector)."""
    sel = getattr(obj, "selector", None)
    if isinstance(sel, LabelSelector):
        return sel
    return LabelSelector(match_labels=dict(sel or {}))


def to_workload_object(kind: str, obj) -> WorkloadObject:
    """Normalize an apiserver workload (Service/RC/RS/StatefulSet) into the
    scheduler's WorkloadObject view (api/types.py) — the GetPodServices /
    GetPodControllers lister adaptation. The scheduler's spread/service-
    affinity code calls .selects(pod), which the raw api objects lack."""
    sel = selector_of(obj)
    return WorkloadObject(
        kind, obj.name, getattr(obj, "namespace", "default"),
        match_labels=dict(sel.match_labels),
        match_expressions=list(sel.match_expressions),
        resource_version=getattr(obj, "resource_version", 0))


def pods_matching(obj, pods: List[Pod]) -> List[Pod]:
    """Live (non-deleted) pods in obj's namespace matching its selector —
    the controller's filteredPods list (replica_set.go syncReplicaSet)."""
    sel = selector_of(obj)
    ns = getattr(obj, "namespace", "default")
    return [p for p in pods
            if p.namespace == ns and not p.deleted and sel.matches(p.labels)]
