"""RBAC API group objects.

Mirror of the rbac.authorization.k8s.io/v1beta1 types the reference serves
(staging/src/k8s.io/api/rbac/v1beta1/types.go) and resolves in
plugin/pkg/auth/authorizer/rbac/rbac.go: PolicyRule matching with verb /
apiGroup / resource / resourceName / nonResourceURL wildcards, Roles bound to
subjects by RoleBindings (namespaced) and ClusterRoleBindings (global).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

WILDCARD = "*"


@dataclass
class PolicyRule:
    """rbac/v1beta1 PolicyRule (types.go:47-76)."""

    verbs: List[str] = field(default_factory=list)
    api_groups: List[str] = field(default_factory=list)
    resources: List[str] = field(default_factory=list)
    resource_names: List[str] = field(default_factory=list)
    non_resource_urls: List[str] = field(default_factory=list)

    def matches_verb(self, verb: str) -> bool:
        return WILDCARD in self.verbs or verb in self.verbs

    def matches_resource(self, resource: str) -> bool:
        if WILDCARD in self.resources:
            return True
        if resource in self.resources:
            return True
        # subresource rules: "pods/status" etc.; "*/status" wildcard form
        if "/" in resource:
            parent, sub = resource.split("/", 1)
            return ("*/" + sub) in self.resources
        return False

    def matches_name(self, name: str) -> bool:
        return not self.resource_names or name in self.resource_names

    def matches_non_resource_url(self, path: str) -> bool:
        for url in self.non_resource_urls:
            if url == WILDCARD or url == path:
                return True
            if url.endswith("*") and path.startswith(url[:-1]):
                return True
        return False


@dataclass
class Subject:
    """rbac Subject (types.go:78-98): kind User | Group | ServiceAccount."""

    kind: str
    name: str
    namespace: str = ""


@dataclass
class Role:
    name: str
    namespace: str = "default"
    rules: List[PolicyRule] = field(default_factory=list)
    resource_version: int = 0


@dataclass
class ClusterRole:
    name: str
    namespace: str = ""  # cluster-scoped
    rules: List[PolicyRule] = field(default_factory=list)
    resource_version: int = 0


@dataclass
class RoleRef:
    kind: str  # Role | ClusterRole
    name: str


@dataclass
class RoleBinding:
    name: str
    namespace: str = "default"
    subjects: List[Subject] = field(default_factory=list)
    role_ref: Optional[RoleRef] = None
    resource_version: int = 0


@dataclass
class ClusterRoleBinding:
    name: str
    namespace: str = ""  # cluster-scoped
    subjects: List[Subject] = field(default_factory=list)
    role_ref: Optional[RoleRef] = None
    resource_version: int = 0


@dataclass
class UserInfo:
    """authentication.k8s.io user.Info (the post-authentication identity —
    staging/src/k8s.io/apiserver/pkg/authentication/user/user.go)."""

    name: str
    groups: List[str] = field(default_factory=list)
    uid: str = ""
    extra: dict = field(default_factory=dict)

    def in_group(self, g: str) -> bool:
        return g in self.groups


SYSTEM_MASTERS = "system:masters"
SYSTEM_AUTHENTICATED = "system:authenticated"
SYSTEM_UNAUTHENTICATED = "system:unauthenticated"
NODES_GROUP = "system:nodes"
SERVICE_ACCOUNTS_GROUP = "system:serviceaccounts"


def bootstrap_cluster_roles() -> List[ClusterRole]:
    """The bootstrap policy slice relevant to the built-in components —
    plugin/pkg/auth/authorizer/rbac/bootstrappolicy/policy.go: cluster-admin,
    admin/edit/view aggregates (flattened), and the component roles the
    scheduler/controller-manager/kubelet/proxy run under."""
    rule = PolicyRule
    return [
        ClusterRole("cluster-admin", rules=[
            rule(verbs=[WILDCARD], api_groups=[WILDCARD], resources=[WILDCARD]),
            rule(verbs=[WILDCARD], non_resource_urls=[WILDCARD]),
        ]),
        ClusterRole("admin", rules=[
            rule(verbs=[WILDCARD], api_groups=[WILDCARD], resources=[WILDCARD]),
        ]),
        ClusterRole("edit", rules=[
            rule(verbs=["get", "list", "watch", "create", "update", "patch",
                        "delete"],
                 api_groups=[WILDCARD], resources=[WILDCARD]),
        ]),
        ClusterRole("view", rules=[
            rule(verbs=["get", "list", "watch"], api_groups=[WILDCARD],
                 resources=[WILDCARD]),
        ]),
        ClusterRole("system:kube-scheduler", rules=[
            rule(verbs=["get", "list", "watch"], api_groups=[""],
                 resources=["pods", "nodes", "persistentvolumes",
                            "persistentvolumeclaims", "services",
                            "replicationcontrollers", "replicasets",
                            "statefulsets"]),
            rule(verbs=["create"], api_groups=[""],
                 resources=["pods/binding", "bindings", "events"]),
            rule(verbs=["update", "patch"], api_groups=[""],
                 resources=["pods/status", "events"]),
            rule(verbs=["get", "create", "update"], api_groups=[""],
                 resources=["endpoints", "configmaps"]),  # leader election
        ]),
        ClusterRole("system:kube-controller-manager", rules=[
            rule(verbs=[WILDCARD], api_groups=[WILDCARD],
                 resources=[WILDCARD]),
        ]),
        ClusterRole("system:node", rules=[
            rule(verbs=["get", "list", "watch"], api_groups=[""],
                 resources=["pods", "services", "endpoints", "nodes"]),
            # secrets/configmaps/PV/PVC are deliberately ABSENT: access is
            # granted per-object by the NodeAuthorizer's reachability check
            # (get of objects referenced by pods bound to the node) — an
            # RBAC grant here would bypass that scoping via union semantics
            # (the reference drops these from the role when Node
            # authorization is enabled)
            rule(verbs=["create", "update", "patch", "delete"],
                 api_groups=[""],
                 resources=["nodes", "nodes/status", "pods", "pods/status",
                            "events"]),
        ]),
        ClusterRole("system:node-proxier", rules=[
            rule(verbs=["get", "list", "watch"], api_groups=[""],
                 resources=["services", "endpoints", "nodes"]),
            rule(verbs=["create", "update", "patch"], api_groups=[""],
                 resources=["events"]),
        ]),
    ]


def bootstrap_cluster_role_bindings() -> List[ClusterRoleBinding]:
    """bootstrappolicy/policy.go ClusterRoleBindings: system:masters ->
    cluster-admin, component users -> component roles, nodes group ->
    system:node."""
    return [
        ClusterRoleBinding(
            "cluster-admin",
            subjects=[Subject("Group", SYSTEM_MASTERS)],
            role_ref=RoleRef("ClusterRole", "cluster-admin")),
        ClusterRoleBinding(
            "system:kube-scheduler",
            subjects=[Subject("User", "system:kube-scheduler")],
            role_ref=RoleRef("ClusterRole", "system:kube-scheduler")),
        ClusterRoleBinding(
            "system:kube-controller-manager",
            subjects=[Subject("User", "system:kube-controller-manager")],
            role_ref=RoleRef("ClusterRole", "system:kube-controller-manager")),
        ClusterRoleBinding(
            "system:node",
            subjects=[Subject("Group", NODES_GROUP)],
            role_ref=RoleRef("ClusterRole", "system:node")),
        ClusterRoleBinding(
            "system:node-proxier",
            subjects=[Subject("User", "system:kube-proxy")],
            role_ref=RoleRef("ClusterRole", "system:node-proxier")),
    ]
