"""Cluster-scoped / config API objects consumed by the apiserver chain:
quota, limits, service accounts, secrets, configmaps, disruption budgets.

References: pkg/api/types.go ResourceQuota/LimitRange/ServiceAccount/Secret/
ConfigMap; pkg/apis/policy/types.go PodDisruptionBudget + Eviction
(the pods/eviction subresource consumes Eviction,
pkg/registry/core/pod/storage/eviction.go).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubernetes_tpu.api.types import LabelSelector


@dataclass
class ResourceQuota:
    """ResourceQuota (pkg/api/types.go; enforced by the resourcequota
    admission controller + recomputed by the quota controller). `hard` and
    `used` are resource-name -> integer quantity (canonical units: millicores
    for cpu, bytes for memory, counts otherwise)."""

    name: str
    namespace: str = "default"
    hard: Dict[str, int] = field(default_factory=dict)
    used: Dict[str, int] = field(default_factory=dict)
    # scopes: Terminating | NotTerminating | BestEffort | NotBestEffort
    scopes: List[str] = field(default_factory=list)
    resource_version: int = 0


@dataclass
class LimitRangeItem:
    """LimitRangeItem (type Container|Pod): min/max/default/defaultRequest
    per resource name."""

    type: str = "Container"
    min: Dict[str, int] = field(default_factory=dict)
    max: Dict[str, int] = field(default_factory=dict)
    default: Dict[str, int] = field(default_factory=dict)  # default limits
    default_request: Dict[str, int] = field(default_factory=dict)


@dataclass
class LimitRange:
    name: str
    namespace: str = "default"
    limits: List[LimitRangeItem] = field(default_factory=list)
    resource_version: int = 0


@dataclass
class ServiceAccount:
    name: str
    namespace: str = "default"
    secrets: List[str] = field(default_factory=list)  # token secret names
    image_pull_secrets: List[str] = field(default_factory=list)
    automount_token: bool = True
    resource_version: int = 0
    uid: str = ""


@dataclass
class Secret:
    name: str
    namespace: str = "default"
    type: str = "Opaque"  # kubernetes.io/service-account-token for SA tokens
    data: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    resource_version: int = 0


@dataclass
class ConfigMap:
    name: str
    namespace: str = "default"
    data: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    resource_version: int = 0


@dataclass
class PodDisruptionBudget:
    """policy/v1beta1 PDB (pkg/apis/policy/types.go): minAvailable gate
    consumed by the eviction subresource + maintained by the disruption
    controller."""

    name: str
    namespace: str = "default"
    min_available: int = 0
    selector: Optional[LabelSelector] = None
    # status (disruption controller): currently healthy / allowed disruptions
    current_healthy: int = 0
    desired_healthy: int = 0
    disruptions_allowed: int = 0
    expected_pods: int = 0
    resource_version: int = 0


@dataclass
class StorageClass:
    """storage.k8s.io/v1 StorageClass (staging/src/k8s.io/api/storage/v1/
    types.go): the provisioner + parameters the PV dynamic-provisioning
    story keys off; cluster-scoped."""

    name: str
    provisioner: str = "kubernetes.io/no-provisioner"
    parameters: Dict[str, str] = field(default_factory=dict)
    reclaim_policy: str = "Delete"  # Delete | Retain
    # the is-default-class marker (the beta annotation in v1.7) the
    # StorageClassDefault admission plugin keys on
    is_default: bool = False
    namespace: str = ""  # cluster-scoped; kept for store uniformity
    resource_version: int = 0


@dataclass
class Eviction:
    """The pods/eviction subresource body."""

    pod_name: str
    namespace: str = "default"


@dataclass
class CertificateSigningRequest:
    """certificates.k8s.io CSR (pkg/apis/certificates/types.go): a kubelet
    requests a client identity; csrapproving auto-approves node requests
    from bootstrap identities, csrsigning signs approved requests. The
    'certificate' issued is the signed identity record CertAuthenticator
    verifies (auth/authn.py)."""

    name: str
    namespace: str = ""  # cluster-scoped
    requestor: str = ""  # authenticated user who posted the CSR
    groups: List[str] = field(default_factory=list)
    cn: str = ""  # requested common name (system:node:<name>)
    orgs: List[str] = field(default_factory=list)  # requested groups
    approved: bool = False
    denied: bool = False
    certificate: Optional[dict] = None  # signed record once issued
    resource_version: int = 0
