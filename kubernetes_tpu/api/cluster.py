"""Cluster-scoped / config API objects consumed by the apiserver chain:
quota, limits, service accounts, secrets, configmaps, disruption budgets.

References: pkg/api/types.go ResourceQuota/LimitRange/ServiceAccount/Secret/
ConfigMap; pkg/apis/policy/types.go PodDisruptionBudget + Eviction
(the pods/eviction subresource consumes Eviction,
pkg/registry/core/pod/storage/eviction.go).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubernetes_tpu.api.types import LabelSelector


@dataclass
class ResourceQuota:
    """ResourceQuota (pkg/api/types.go; enforced by the resourcequota
    admission controller + recomputed by the quota controller). `hard` and
    `used` are resource-name -> integer quantity (canonical units: millicores
    for cpu, bytes for memory, counts otherwise)."""

    name: str
    namespace: str = "default"
    hard: Dict[str, int] = field(default_factory=dict)
    used: Dict[str, int] = field(default_factory=dict)
    # scopes: Terminating | NotTerminating | BestEffort | NotBestEffort
    scopes: List[str] = field(default_factory=list)
    resource_version: int = 0


@dataclass
class LimitRangeItem:
    """LimitRangeItem (type Container|Pod): min/max/default/defaultRequest
    per resource name."""

    type: str = "Container"
    min: Dict[str, int] = field(default_factory=dict)
    max: Dict[str, int] = field(default_factory=dict)
    default: Dict[str, int] = field(default_factory=dict)  # default limits
    default_request: Dict[str, int] = field(default_factory=dict)


@dataclass
class LimitRange:
    name: str
    namespace: str = "default"
    limits: List[LimitRangeItem] = field(default_factory=list)
    resource_version: int = 0


@dataclass
class ServiceAccount:
    name: str
    namespace: str = "default"
    secrets: List[str] = field(default_factory=list)  # token secret names
    image_pull_secrets: List[str] = field(default_factory=list)
    automount_token: bool = True
    resource_version: int = 0
    uid: str = ""


@dataclass
class Secret:
    name: str
    namespace: str = "default"
    type: str = "Opaque"  # kubernetes.io/service-account-token for SA tokens
    data: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    resource_version: int = 0


@dataclass
class ConfigMap:
    name: str
    namespace: str = "default"
    data: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    resource_version: int = 0


@dataclass
class PodDisruptionBudget:
    """policy/v1beta1 PDB (pkg/apis/policy/types.go): minAvailable gate
    consumed by the eviction subresource + maintained by the disruption
    controller."""

    name: str
    namespace: str = "default"
    min_available: int = 0
    selector: Optional[LabelSelector] = None
    # status (disruption controller): currently healthy / allowed disruptions
    current_healthy: int = 0
    desired_healthy: int = 0
    disruptions_allowed: int = 0
    expected_pods: int = 0
    resource_version: int = 0


@dataclass
class Eviction:
    """The pods/eviction subresource body."""

    pod_name: str
    namespace: str = "default"
