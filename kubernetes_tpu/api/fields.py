"""Field selectors — apimachinery/pkg/fields.

The reference's second selection axis next to labels: `kubectl get pods
--field-selector spec.nodeName=n1,status.phase!=Running` and the
kubelet's pod source LIST (spec.nodeName=<node>, pkg/kubelet/config/
apiserver.go NewSourceApiserver). Selection strings parse to =/==/!=
requirements ANDed together (fields/selector.go ParseSelector); each
kind exposes its selectable field set through a conversion much like
the registry strategies' GetAttrs (pkg/registry/core/pod/strategy.go
PodToSelectableFields: metadata.name, metadata.namespace, spec.nodeName,
spec.schedulerName, spec.restartPolicy, status.phase).

Unknown field keys are an error, like the reference's
field-label conversion failing on unsupported selectors.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Tuple


class FieldSelectorError(Exception):
    pass


@dataclass(frozen=True)
class FieldSelector:
    # (key, op, value) with op in {"=", "!="}
    requirements: Tuple[Tuple[str, str, str], ...] = ()

    def matches(self, fields: Dict[str, str]) -> bool:
        for key, op, value in self.requirements:
            if key not in fields:
                raise FieldSelectorError(
                    f'field label not supported: "{key}"')
            if op == "=" and fields[key] != value:
                return False
            if op == "!=" and fields[key] == value:
                return False
        return True

    @property
    def empty(self) -> bool:
        return not self.requirements


EVERYTHING = FieldSelector()


def parse_field_selector(text: str) -> FieldSelector:
    """fields/selector.go ParseSelector: comma-separated k=v / k==v /
    k!=v terms; empty string selects everything."""
    reqs: List[Tuple[str, str, str]] = []
    for raw in filter(None, (t.strip() for t in (text or "").split(","))):
        if "!=" in raw:
            key, _, value = raw.partition("!=")
            op = "!="
        elif "==" in raw:
            key, _, value = raw.partition("==")
            op = "="
        elif "=" in raw:
            key, _, value = raw.partition("=")
            op = "="
        else:
            raise FieldSelectorError(
                f"invalid field selector term {raw!r}")
        key, value = key.strip(), value.strip()
        if not key:
            raise FieldSelectorError(
                f"invalid field selector term {raw!r}")
        reqs.append((key, op, value))
    return FieldSelector(tuple(reqs))


# -------------------------------------------------- per-kind field sets


def _meta_fields(obj: Any) -> Dict[str, str]:
    return {"metadata.name": getattr(obj, "name", ""),
            "metadata.namespace": getattr(obj, "namespace", "")}


def pod_fields(pod: Any) -> Dict[str, str]:
    """pod/strategy.go PodToSelectableFields."""
    out = _meta_fields(pod)
    out["spec.nodeName"] = pod.node_name or ""
    out["spec.schedulerName"] = getattr(pod, "scheduler_name", "") or ""
    out["spec.restartPolicy"] = getattr(pod, "restart_policy", "") or ""
    out["status.phase"] = getattr(pod, "phase", "") or ""
    return out


def node_fields(node: Any) -> Dict[str, str]:
    """node/strategy.go NodeToSelectableFields (+ spec.unschedulable)."""
    out = _meta_fields(node)
    out["spec.unschedulable"] = \
        "true" if getattr(node, "unschedulable", False) else "false"
    return out


def event_fields(ev: Any) -> Dict[str, str]:
    """event strategy GetAttrs: involvedObject + reason + type."""
    out = _meta_fields(ev)
    out["involvedObject.name"] = getattr(ev, "object_key", "") or ""
    out["reason"] = getattr(ev, "reason", "") or ""
    out["type"] = getattr(ev, "type", "") or ""
    return out


_FIELD_FUNCS = {
    "Pod": pod_fields,
    "Node": node_fields,
    "Event": event_fields,
}

_META_KEYS = frozenset({"metadata.name", "metadata.namespace"})


class _AnyStub:
    """Answers "" for every attribute — lets the selectable key sets be
    DERIVED from the field functions themselves (run each fn once against
    a stub and record the keys it emits), so a new field added to
    pod_fields is immediately selectable with no parallel set or
    per-kind stub object to keep in sync."""

    def __getattr__(self, name):
        return ""


SELECTABLE_KEYS = {kind: frozenset(fn(_AnyStub()).keys())
                   for kind, fn in _FIELD_FUNCS.items()}


def selectable_fields(kind: str, obj: Any) -> Dict[str, str]:
    """GetAttrs per kind; every kind supports the metadata pair."""
    fn = _FIELD_FUNCS.get(kind)
    return fn(obj) if fn is not None else _meta_fields(obj)


def validate_selector(kind: str, selector: FieldSelector) -> None:
    """Reject unsupported field labels up front, independent of cluster
    contents — the reference fails the field-label conversion at request
    time, not per matched object (an empty cluster must NOT make an
    invalid selector succeed)."""
    allowed = SELECTABLE_KEYS.get(kind, _META_KEYS)
    for key, _op, _v in selector.requirements:
        if key not in allowed:
            raise FieldSelectorError(
                f'field label not supported: "{key}"')


def filter_objects(kind: str, objs: List[Any],
                   selector: FieldSelector) -> List[Any]:
    if selector.empty:
        return objs
    validate_selector(kind, selector)
    return [o for o in objs
            if selector.matches(selectable_fields(kind, o))]
