"""Kubernetes JSON wire-format codecs.

Decodes real k8s v1 JSON objects (Pod, Node, the scheduler-extender wire
structs) into the framework's object model, so the extender sidecar speaks
the reference's exact HTTP contract (plugin/pkg/scheduler/core/extender.go:226
`send` posts JSON-encoded ExtenderArgs; structs at
plugin/pkg/scheduler/api/types.go:158-204 & their v1 mirror api/v1/types.go).

Includes a resource.Quantity parser
(staging/src/k8s.io/apimachinery/pkg/api/resource/quantity.go semantics:
plain/decimal numbers, "m" milli suffix, decimal K/M/G/T/P/E and binary
Ki/Mi/Gi/Ti/Pi/Ei suffixes, scientific notation). CPU decodes to millicores
(MilliValue), everything else to integer units rounded up (Value)."""

from __future__ import annotations

import math
from fractions import Fraction
from typing import Any, Dict, List, Optional, Tuple

from kubernetes_tpu.api.types import (
    Affinity,
    Container,
    ContainerPort,
    LabelSelector,
    Node,
    NodeAffinity,
    NodeCondition,
    NodeSelectorTerm,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodSecurityContext,
    Probe,
    Resource,
    SecurityContext,
    SelectorOperator,
    SelectorRequirement,
    Taint,
    TaintEffect,
    Toleration,
    TolerationOperator,
    Volume,
    VolumeKind,
)

_SUFFIX = {
    "k": 10 ** 3, "M": 10 ** 6, "G": 10 ** 9, "T": 10 ** 12,
    "P": 10 ** 15, "E": 10 ** 18,
    "Ki": 2 ** 10, "Mi": 2 ** 20, "Gi": 2 ** 30, "Ti": 2 ** 40,
    "Pi": 2 ** 50, "Ei": 2 ** 60,
}


def parse_quantity(s) -> Fraction:
    """-> exact Fraction of base units."""
    if isinstance(s, (int, float)):
        return Fraction(s).limit_denominator(10 ** 9)
    s = s.strip()
    if not s:
        return Fraction(0)
    for suf in ("Ki", "Mi", "Gi", "Ti", "Pi", "Ei", "k", "M", "G", "T", "P", "E"):
        if s.endswith(suf):
            return Fraction(s[: -len(suf)]) * _SUFFIX[suf]
    if s.endswith("m"):
        return Fraction(s[:-1]) / 1000
    return Fraction(s)


def quantity_milli(s) -> int:
    """MilliValue: ceil to millis (quantity.go ScaledValue(resource.Milli))."""
    return int(math.ceil(parse_quantity(s) * 1000))


def quantity_value(s) -> int:
    """Value: ceil to whole units."""
    return int(math.ceil(parse_quantity(s)))


def decode_resource_list(rl: Optional[Dict[str, Any]]) -> Dict[str, int]:
    """k8s ResourceList -> canonical int units (cpu: millicores; rest: value)."""
    out: Dict[str, int] = {}
    for name, q in (rl or {}).items():
        if name == "cpu":
            out["cpu"] = quantity_milli(q)
        elif name == "memory":
            out["memory"] = quantity_value(q)
        else:
            out[name] = quantity_value(q)
    return out


# ---------------------------------------------------------------------------
# selectors / affinity
# ---------------------------------------------------------------------------


def _decode_requirements(reqs: Optional[List[Dict]]) -> List[SelectorRequirement]:
    out = []
    for r in reqs or []:
        out.append(SelectorRequirement(
            key=r.get("key", ""),
            operator=SelectorOperator(r.get("operator", "In")),
            values=list(r.get("values") or []),
        ))
    return out


def _decode_node_affinity(na: Optional[Dict]) -> Optional[NodeAffinity]:
    if na is None:
        return None
    required = None
    req = na.get("requiredDuringSchedulingIgnoredDuringExecution")
    if req is not None:
        required = [NodeSelectorTerm(_decode_requirements(t.get("matchExpressions")))
                    for t in req.get("nodeSelectorTerms") or []]
    preferred = []
    for p in na.get("preferredDuringSchedulingIgnoredDuringExecution") or []:
        pref = p.get("preference") or {}
        preferred.append((int(p.get("weight", 1)),
                          NodeSelectorTerm(_decode_requirements(
                              pref.get("matchExpressions")))))
    return NodeAffinity(required_terms=required, preferred_terms=preferred)


def _decode_label_selector(ls: Optional[Dict]) -> Optional[LabelSelector]:
    if ls is None:
        return None
    return LabelSelector(
        match_labels=dict(ls.get("matchLabels") or {}),
        match_expressions=_decode_requirements(ls.get("matchExpressions")),
    )


def _decode_pod_affinity_terms(terms: Optional[List[Dict]]) -> List[PodAffinityTerm]:
    out = []
    for t in terms or []:
        out.append(PodAffinityTerm(
            label_selector=_decode_label_selector(t.get("labelSelector")),
            namespaces=list(t.get("namespaces") or []),
            topology_key=t.get("topologyKey", ""),
        ))
    return out


def _decode_pod_affinity(pa: Optional[Dict]) -> Optional[PodAffinity]:
    if pa is None:
        return None
    preferred = []
    for w in pa.get("preferredDuringSchedulingIgnoredDuringExecution") or []:
        term = w.get("podAffinityTerm") or {}
        preferred.append((int(w.get("weight", 1)),
                          _decode_pod_affinity_terms([term])[0]))
    return PodAffinity(
        required_terms=_decode_pod_affinity_terms(
            pa.get("requiredDuringSchedulingIgnoredDuringExecution")),
        preferred_terms=preferred,
    )


def decode_affinity(aff: Optional[Dict]) -> Optional[Affinity]:
    if not aff:
        return None
    return Affinity(
        node_affinity=_decode_node_affinity(aff.get("nodeAffinity")),
        pod_affinity=_decode_pod_affinity(aff.get("podAffinity")),
        pod_anti_affinity=_decode_pod_affinity(aff.get("podAntiAffinity")),
    )


# -- encoders inverting the decoders above (conversion round-trip support) --


def _encode_requirements(reqs: List[SelectorRequirement]) -> List[Dict]:
    return [{"key": r.key,
             "operator": r.operator.value
             if hasattr(r.operator, "value") else r.operator,
             "values": list(r.values)} for r in reqs]


def _encode_label_selector(ls: Optional[LabelSelector]) -> Optional[Dict]:
    if ls is None:
        return None  # nil selector (matches nothing) != empty (matches all)
    out: Dict[str, Any] = {}
    if ls.match_labels:
        out["matchLabels"] = dict(ls.match_labels)
    if ls.match_expressions:
        out["matchExpressions"] = _encode_requirements(ls.match_expressions)
    return out


def _encode_pod_affinity_term(t: PodAffinityTerm) -> Dict:
    out: Dict[str, Any] = {"topologyKey": t.topology_key}
    sel = _encode_label_selector(t.label_selector)
    if sel is not None:
        out["labelSelector"] = sel
    if t.namespaces:
        out["namespaces"] = list(t.namespaces)
    return out


def _encode_pod_affinity(pa: Optional[PodAffinity]) -> Optional[Dict]:
    if pa is None:
        return None
    out: Dict[str, Any] = {}
    if pa.required_terms:
        out["requiredDuringSchedulingIgnoredDuringExecution"] = [
            _encode_pod_affinity_term(t) for t in pa.required_terms]
    if pa.preferred_terms:
        out["preferredDuringSchedulingIgnoredDuringExecution"] = [
            {"weight": w, "podAffinityTerm": _encode_pod_affinity_term(t)}
            for w, t in pa.preferred_terms]
    # a present-but-empty PodAffinity must stay present ({}), not vanish —
    # decode({'podAffinity': {}}) produced it and must get it back
    return out


def encode_affinity(aff: Optional[Affinity]) -> Optional[Dict]:
    """Inverse of decode_affinity: decode(encode(x)) == x, preserving the
    nil-vs-empty distinctions the predicates read (required_terms None vs
    [], nil vs empty labelSelector)."""
    if aff is None:
        return None
    out: Dict[str, Any] = {}
    na = aff.node_affinity
    if na is not None:
        d: Dict[str, Any] = {}
        if na.required_terms is not None:
            d["requiredDuringSchedulingIgnoredDuringExecution"] = {
                "nodeSelectorTerms": [
                    {"matchExpressions":
                     _encode_requirements(t.match_expressions)}
                    for t in na.required_terms]}
        if na.preferred_terms:
            d["preferredDuringSchedulingIgnoredDuringExecution"] = [
                {"weight": w, "preference": {
                    "matchExpressions":
                    _encode_requirements(t.match_expressions)}}
                for w, t in na.preferred_terms]
        out["nodeAffinity"] = d  # {} round-trips to NodeAffinity(None, [])
    pa = _encode_pod_affinity(aff.pod_affinity)
    if pa is not None:
        out["podAffinity"] = pa
    paa = _encode_pod_affinity(aff.pod_anti_affinity)
    if paa is not None:
        out["podAntiAffinity"] = paa
    return out or None


# ---------------------------------------------------------------------------
# Pod / Node
# ---------------------------------------------------------------------------


def decode_volume(v: Dict[str, Any]) -> Volume:
    """v1 VolumeSource union -> scheduler-relevant identity
    (the sources read by predicates.go:128-374; others -> OTHER)."""
    name = v.get("name", "")
    if "gcePersistentDisk" in v:
        s = v["gcePersistentDisk"] or {}
        return Volume(name=name, kind=VolumeKind.GCE_PD,
                      volume_id=s.get("pdName", ""),
                      read_only=bool(s.get("readOnly", False)))
    if "awsElasticBlockStore" in v:
        s = v["awsElasticBlockStore"] or {}
        return Volume(name=name, kind=VolumeKind.AWS_EBS,
                      volume_id=s.get("volumeID", ""),
                      read_only=bool(s.get("readOnly", False)))
    if "rbd" in v:
        s = v["rbd"] or {}
        return Volume(name=name, kind=VolumeKind.RBD,
                      monitors=list(s.get("monitors") or []),
                      pool=s.get("pool", ""), image=s.get("image", ""),
                      read_only=bool(s.get("readOnly", False)))
    if "iscsi" in v:
        s = v["iscsi"] or {}
        return Volume(name=name, kind=VolumeKind.ISCSI,
                      volume_id=s.get("iqn", ""),
                      read_only=bool(s.get("readOnly", False)))
    if "azureDisk" in v:
        s = v["azureDisk"] or {}
        return Volume(name=name, kind=VolumeKind.AZURE_DISK,
                      volume_id=s.get("diskName", ""),
                      read_only=bool(s.get("readOnly", False)))
    if "persistentVolumeClaim" in v:
        s = v["persistentVolumeClaim"] or {}
        return Volume(name=name, kind=VolumeKind.PVC,
                      volume_id=s.get("claimName", ""),
                      read_only=bool(s.get("readOnly", False)))
    if "secret" in v:
        s = v["secret"] or {}
        return Volume(name=name, kind=VolumeKind.SECRET,
                      volume_id=s.get("secretName", ""))
    if "configMap" in v:
        s = v["configMap"] or {}
        return Volume(name=name, kind=VolumeKind.CONFIG_MAP,
                      volume_id=s.get("name", ""))
    return Volume(name=name, kind=VolumeKind.OTHER)


def encode_volume(v: Volume) -> Dict[str, Any]:
    kind = VolumeKind(v.kind)
    out: Dict[str, Any] = {"name": v.name}
    if kind == VolumeKind.GCE_PD:
        out["gcePersistentDisk"] = {"pdName": v.volume_id,
                                    "readOnly": v.read_only}
    elif kind == VolumeKind.AWS_EBS:
        out["awsElasticBlockStore"] = {"volumeID": v.volume_id,
                                       "readOnly": v.read_only}
    elif kind == VolumeKind.RBD:
        out["rbd"] = {"monitors": list(v.monitors), "pool": v.pool,
                      "image": v.image, "readOnly": v.read_only}
    elif kind == VolumeKind.ISCSI:
        out["iscsi"] = {"iqn": v.volume_id, "readOnly": v.read_only}
    elif kind == VolumeKind.AZURE_DISK:
        out["azureDisk"] = {"diskName": v.volume_id,
                            "readOnly": v.read_only}
    elif kind == VolumeKind.PVC:
        out["persistentVolumeClaim"] = {"claimName": v.volume_id,
                                        "readOnly": v.read_only}
    elif kind == VolumeKind.SECRET:
        out["secret"] = {"secretName": v.volume_id}
    elif kind == VolumeKind.CONFIG_MAP:
        out["configMap"] = {"name": v.volume_id}
    return out


def decode_pod(obj: Dict[str, Any]) -> Pod:
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    def _decode_sc(s, pod_level: bool):
        if not s:
            return None
        if pod_level:
            return PodSecurityContext(
                run_as_user=(int(s["runAsUser"])
                             if s.get("runAsUser") is not None else None),
                run_as_non_root=s.get("runAsNonRoot"))
        return SecurityContext(
            privileged=s.get("privileged"),
            run_as_user=(int(s["runAsUser"])
                         if s.get("runAsUser") is not None else None),
            run_as_non_root=s.get("runAsNonRoot"),
            read_only_root_filesystem=s.get("readOnlyRootFilesystem"))

    def _decode_probe(p):
        if not p:
            return None
        kind = "exec"
        for k in ("httpGet", "tcpSocket", "exec"):
            if p.get(k) is not None:
                kind = k
                break
        return Probe(kind=kind,
                     initial_delay_s=float(p.get("initialDelaySeconds", 0)),
                     period_s=float(p.get("periodSeconds", 10)),
                     failure_threshold=int(p.get("failureThreshold", 3)),
                     success_threshold=int(p.get("successThreshold", 1)))

    containers = []
    for c in spec.get("containers") or []:
        res = c.get("resources") or {}
        containers.append(Container(
            name=c.get("name", ""),
            image=c.get("image", ""),
            requests=decode_resource_list(res.get("requests")),
            limits=decode_resource_list(res.get("limits")),
            ports=[ContainerPort(host_port=int(p.get("hostPort", 0)),
                                 container_port=int(p.get("containerPort", 0)),
                                 protocol=p.get("protocol", "TCP"))
                   for p in c.get("ports") or []],
            liveness_probe=_decode_probe(c.get("livenessProbe")),
            readiness_probe=_decode_probe(c.get("readinessProbe")),
            security_context=_decode_sc(c.get("securityContext"), False),
        ))
    tolerations = []
    for t in spec.get("tolerations") or []:
        eff = t.get("effect") or None
        tolerations.append(Toleration(
            key=t.get("key", ""),
            operator=TolerationOperator(t.get("operator", "Equal")),
            value=t.get("value", ""),
            effect=TaintEffect(eff) if eff else None,
        ))
    owner_kind, owner_name, owner_uid = "", "", ""
    for ref in meta.get("ownerReferences") or []:
        if ref.get("controller"):
            owner_kind = ref.get("kind", "")
            owner_name = ref.get("name", "")
            owner_uid = ref.get("uid", "")
            break
    return Pod(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        uid=meta.get("uid", ""),
        labels=dict(meta.get("labels") or {}),
        annotations=dict(meta.get("annotations") or {}),
        containers=containers,
        volumes=[decode_volume(v) for v in spec.get("volumes") or []],
        node_name=spec.get("nodeName", ""),
        node_selector=dict(spec.get("nodeSelector") or {}),
        affinity=decode_affinity(spec.get("affinity")),
        tolerations=tolerations,
        scheduler_name=spec.get("schedulerName", "default-scheduler"),
        priority=int(spec.get("priority") or 0),
        restart_policy=spec.get("restartPolicy", "Always"),
        host_network=bool(spec.get("hostNetwork", False)),
        security_context=_decode_sc(spec.get("securityContext"), True),
        owner_kind=owner_kind,
        owner_name=owner_name,
        owner_uid=owner_uid,
        deleted=meta.get("deletionTimestamp") is not None,
    )


def _decode_resource(rl: Dict[str, int]) -> Resource:
    extended = {k: v for k, v in rl.items()
                if k not in ("cpu", "memory", "pods",
                             "nvidia.com/gpu", "alpha.kubernetes.io/nvidia-gpu",
                             "storage.kubernetes.io/scratch",
                             "storage.kubernetes.io/overlay")}
    return Resource(
        milli_cpu=rl.get("cpu", 0),
        memory=rl.get("memory", 0),
        nvidia_gpu=rl.get("nvidia.com/gpu",
                          rl.get("alpha.kubernetes.io/nvidia-gpu", 0)),
        storage_scratch=rl.get("storage.kubernetes.io/scratch", 0),
        storage_overlay=rl.get("storage.kubernetes.io/overlay", 0),
        extended=extended,
    )


def decode_node(obj: Dict[str, Any]) -> Node:
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    status = obj.get("status") or {}
    alloc_rl = decode_resource_list(status.get("allocatable")
                                    or status.get("capacity"))
    alloc = _decode_resource(alloc_rl)
    taints = []
    for t in spec.get("taints") or []:
        taints.append(Taint(t.get("key", ""), t.get("value", ""),
                            TaintEffect(t.get("effect", "NoSchedule"))))
    conditions = [NodeCondition(c.get("type", ""), c.get("status", "Unknown"))
                  for c in status.get("conditions") or []]
    # a capacity distinct from allocatable (node-allocatable reservation)
    capacity = None
    if status.get("capacity") and status.get("allocatable") \
            and status["capacity"] != status["allocatable"]:
        capacity = _decode_resource(
            decode_resource_list(status["capacity"]))
    return Node(
        name=meta.get("name", ""),
        labels=dict(meta.get("labels") or {}),
        annotations=dict(meta.get("annotations") or {}),
        allocatable=alloc,
        capacity=capacity,
        allowed_pod_number=alloc_rl.get("pods", 110),
        taints=taints,
        unschedulable=bool(spec.get("unschedulable", False)),
        conditions=conditions,
    )


def encode_pod(pod: Pod) -> Dict[str, Any]:
    """Inverse of decode_pod over the full spec surface it reads —
    decode(encode(p)) == p for every wire-carried field (the codec
    round-trip invariant the core-group conversion tests pin)."""
    def _enc_sc(s) -> Optional[Dict[str, Any]]:
        if s is None:
            return None
        out = {}
        if getattr(s, "privileged", None) is not None:
            out["privileged"] = s.privileged
        if s.run_as_user is not None:
            out["runAsUser"] = s.run_as_user
        if s.run_as_non_root is not None:
            out["runAsNonRoot"] = s.run_as_non_root
        if getattr(s, "read_only_root_filesystem", None) is not None:
            out["readOnlyRootFilesystem"] = s.read_only_root_filesystem
        return out or None

    def _enc_rl(rl: Dict[str, int]) -> Dict[str, str]:
        return {k: (f"{v}m" if k == "cpu" else str(v))
                for k, v in rl.items()}

    def _enc_probe(p) -> Optional[Dict[str, Any]]:
        if p is None:
            return None
        return {p.kind: {},
                "initialDelaySeconds": p.initial_delay_s,
                "periodSeconds": p.period_s,
                "failureThreshold": p.failure_threshold,
                "successThreshold": p.success_threshold}

    containers = []
    for c in pod.containers:
        enc = {
            "name": c.name, "image": c.image,
            "resources": {"requests": _enc_rl(c.requests),
                          **({"limits": _enc_rl(c.limits)}
                             if c.limits else {})},
            "ports": [{"hostPort": p.host_port, "containerPort": p.container_port,
                       "protocol": p.protocol} for p in c.ports],
        }
        lp = _enc_probe(c.liveness_probe)
        if lp:
            enc["livenessProbe"] = lp
        rp = _enc_probe(c.readiness_probe)
        if rp:
            enc["readinessProbe"] = rp
        csc = _enc_sc(c.security_context)
        if csc:
            enc["securityContext"] = csc
        containers.append(enc)
    spec: Dict[str, Any] = {
        "containers": containers, "nodeName": pod.node_name,
        "nodeSelector": pod.node_selector,
        "schedulerName": pod.scheduler_name,
        "restartPolicy": pod.restart_policy,
        "volumes": [encode_volume(v) for v in pod.volumes]}
    if pod.priority:
        spec["priority"] = pod.priority
    if pod.tolerations:
        spec["tolerations"] = [
            {"key": t.key,
             "operator": t.operator.value
             if hasattr(t.operator, "value") else t.operator,
             "value": t.value,
             **({"effect": t.effect.value
                 if hasattr(t.effect, "value") else t.effect}
                if t.effect else {})}
            for t in pod.tolerations]
    aff = encode_affinity(pod.affinity)
    if aff is not None:
        spec["affinity"] = aff
    if pod.host_network:
        spec["hostNetwork"] = True
    psc = _enc_sc(pod.security_context)
    if psc:
        spec["securityContext"] = psc
    meta: Dict[str, Any] = {
        "name": pod.name, "namespace": pod.namespace,
        "uid": pod.uid, "labels": pod.labels}
    if pod.annotations:
        meta["annotations"] = dict(pod.annotations)
    if pod.owner_kind:
        meta["ownerReferences"] = [{
            "kind": pod.owner_kind, "name": pod.owner_name,
            "uid": pod.owner_uid, "controller": True}]
    if pod.deleted:
        meta["deletionTimestamp"] = "1970-01-01T00:00:00Z"
    return {"metadata": meta, "spec": spec}


def _encode_resource_list(res, pods: int) -> Dict[str, str]:
    out = {"cpu": f"{res.milli_cpu}m",
           "memory": str(res.memory),
           "pods": str(pods)}
    if res.nvidia_gpu:
        out["nvidia.com/gpu"] = str(res.nvidia_gpu)
    for k, v in res.extended.items():
        out[k] = str(v)
    return out


def encode_node(node: Node) -> Dict[str, Any]:
    alloc = _encode_resource_list(node.allocatable,
                                  node.allowed_pod_number)
    meta: Dict[str, Any] = {"name": node.name, "labels": node.labels}
    if node.annotations:
        meta["annotations"] = dict(node.annotations)
    return {
        "metadata": meta,
        "spec": {
            "unschedulable": node.unschedulable,
            "taints": [{"key": t.key, "value": t.value,
                        "effect": (t.effect.value if isinstance(t.effect, TaintEffect)
                                   else t.effect)} for t in node.taints],
        },
        "status": {
            "allocatable": alloc,
            **({"capacity": _encode_resource_list(
                node.capacity, node.allowed_pod_number)}
               if node.capacity is not None else {}),
            "conditions": [{"type": c.type,
                            "status": (c.status.value if hasattr(c.status, "value")
                                       else c.status)}
                           for c in node.conditions],
        },
    }
