"""Core API object model.

Python-native mirror of the *scheduling-relevant* slice of the Kubernetes v1 API
surface: the fields read by predicates and priorities (reference:
plugin/pkg/scheduler/schedulercache/node_info.go:34-75 and
plugin/pkg/scheduler/algorithm/predicates/predicates.go), plus the objects the
control plane moves around (Binding, events). This is deliberately NOT a port of
staging/src/k8s.io/api/core/v1/types.go (4,738 lines, mostly generated) — the
TPU-native design keeps the host-side object model minimal and puts the scale
axis in dense tensors (see kubernetes_tpu/state/snapshot.py).

All resource quantities are plain integers in canonical units:
  - cpu: millicores (int)
  - memory / storage: bytes (int)
  - gpu / extended resources: counts (int)
mirroring resource.Quantity's MilliValue()/Value() accessors
(staging/src/k8s.io/apimachinery/pkg/api/resource/quantity.go).
"""

from __future__ import annotations

import dataclasses
import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Resources
# ---------------------------------------------------------------------------

# Default requests applied *for priority scoring only* to containers that do
# not specify a request — reference:
# plugin/pkg/scheduler/algorithm/priorities/util/non_zero.go:29-31
DEFAULT_MILLI_CPU_REQUEST = 100
DEFAULT_MEMORY_REQUEST = 200 * 1024 * 1024

# MaxPriority — reference: plugin/pkg/scheduler/api/types.go:33
MAX_PRIORITY = 10


@dataclass
class Resource:
    """Aggregate resource vector.

    Mirrors schedulercache.Resource (reference: schedulercache/node_info.go:65-75):
    MilliCPU, Memory, NvidiaGPU, storage scratch/overlay, plus extended
    (opaque-integer) resources.
    """

    milli_cpu: int = 0
    memory: int = 0
    nvidia_gpu: int = 0
    storage_scratch: int = 0
    storage_overlay: int = 0
    extended: Dict[str, int] = field(default_factory=dict)

    def add(self, other: "Resource") -> "Resource":
        for k, v in other.extended.items():
            self.extended[k] = self.extended.get(k, 0) + v
        self.milli_cpu += other.milli_cpu
        self.memory += other.memory
        self.nvidia_gpu += other.nvidia_gpu
        self.storage_scratch += other.storage_scratch
        self.storage_overlay += other.storage_overlay
        return self

    def sub(self, other: "Resource") -> "Resource":
        for k, v in other.extended.items():
            self.extended[k] = self.extended.get(k, 0) - v
        self.milli_cpu -= other.milli_cpu
        self.memory -= other.memory
        self.nvidia_gpu -= other.nvidia_gpu
        self.storage_scratch -= other.storage_scratch
        self.storage_overlay -= other.storage_overlay
        return self

    def clone(self) -> "Resource":
        return Resource(
            self.milli_cpu,
            self.memory,
            self.nvidia_gpu,
            self.storage_scratch,
            self.storage_overlay,
            dict(self.extended),
        )


# ---------------------------------------------------------------------------
# Selectors / affinity
# ---------------------------------------------------------------------------


class SelectorOperator(str, enum.Enum):
    """Node-selector requirement operators — reference:
    staging/src/k8s.io/api/core/v1/types.go NodeSelectorOperator."""

    IN = "In"
    NOT_IN = "NotIn"
    EXISTS = "Exists"
    DOES_NOT_EXIST = "DoesNotExist"
    GT = "Gt"
    LT = "Lt"


@dataclass
class SelectorRequirement:
    key: str
    operator: SelectorOperator
    values: List[str] = field(default_factory=list)

    def matches_labels(self, labels: Dict[str, str]) -> bool:
        """Evaluate against a label map — semantics of
        labels.Selector.Matches over NodeSelectorRequirementsAsSelector
        (reference: pkg/api/v1/helper/helpers.go NodeSelectorRequirementsAsSelector)."""
        op = SelectorOperator(self.operator)
        present = self.key in labels
        if op == SelectorOperator.EXISTS:
            return present
        if op == SelectorOperator.DOES_NOT_EXIST:
            return not present
        if op == SelectorOperator.IN:
            return present and labels[self.key] in self.values
        if op == SelectorOperator.NOT_IN:
            # k8s labels.Requirement: NotIn fails when key absent? In k8s,
            # NotIn requires the key to exist with value not in set — absent
            # key *matches* NotIn for label selectors built via
            # NodeSelectorRequirementsAsSelector (operator -> selection.NotIn,
            # whose Matches returns true when key is absent).
            return (not present) or labels[self.key] not in self.values
        if op in (SelectorOperator.GT, SelectorOperator.LT):
            if not present or len(self.values) != 1:
                return False
            try:
                lhs = int(labels[self.key])
                rhs = int(self.values[0])
            except ValueError:
                return False
            return lhs > rhs if op == SelectorOperator.GT else lhs < rhs
        return False


@dataclass
class NodeSelectorTerm:
    """Expressions are ANDed; terms in a list are ORed
    (reference: predicates.go:625-646 nodeMatchesNodeSelectorTerms)."""

    match_expressions: List[SelectorRequirement] = field(default_factory=list)

    def matches_labels(self, labels: Dict[str, str]) -> bool:
        if not self.match_expressions:
            # non-nil empty NodeSelectorRequirement list matches no nodes
            # (predicates.go:646 comment, cases 4-5)
            return False
        return all(r.matches_labels(labels) for r in self.match_expressions)


@dataclass
class NodeAffinity:
    # None means "no required terms" (matches everything); [] matches nothing
    # (predicates.go:660-683).
    required_terms: Optional[List[NodeSelectorTerm]] = None
    # (weight, term) pairs — PreferredSchedulingTerm
    preferred_terms: List[Tuple[int, NodeSelectorTerm]] = field(default_factory=list)


@dataclass
class LabelSelector:
    """metav1.LabelSelector: match_labels ANDed with match_expressions.
    nil selector matches nothing in affinity context; empty selector matches
    everything (apimachinery LabelSelectorAsSelector semantics)."""

    match_labels: Dict[str, str] = field(default_factory=dict)
    match_expressions: List[SelectorRequirement] = field(default_factory=list)

    def matches(self, labels: Dict[str, str]) -> bool:
        for k, v in self.match_labels.items():
            if labels.get(k) != v:
                return False
        return all(r.matches_labels(labels) for r in self.match_expressions)


@dataclass
class PodAffinityTerm:
    """reference: v1.PodAffinityTerm — selector over pods, within topology_key
    domains, restricted to namespaces (empty = pod's own namespace)."""

    label_selector: Optional[LabelSelector] = None
    namespaces: List[str] = field(default_factory=list)
    topology_key: str = ""


@dataclass
class PodAffinity:
    required_terms: List[PodAffinityTerm] = field(default_factory=list)
    # (weight, term)
    preferred_terms: List[Tuple[int, PodAffinityTerm]] = field(default_factory=list)


@dataclass
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAffinity] = None


# ---------------------------------------------------------------------------
# Taints / tolerations
# ---------------------------------------------------------------------------


class TaintEffect(str, enum.Enum):
    NO_SCHEDULE = "NoSchedule"
    PREFER_NO_SCHEDULE = "PreferNoSchedule"
    NO_EXECUTE = "NoExecute"


@dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: TaintEffect = TaintEffect.NO_SCHEDULE


class TolerationOperator(str, enum.Enum):
    EXISTS = "Exists"
    EQUAL = "Equal"


@dataclass(frozen=True)
class Toleration:
    """reference: v1.Toleration; ToleratesTaint semantics in
    staging/src/k8s.io/api/core/v1/toleration.go — empty key with Exists
    tolerates everything; empty effect matches all effects."""

    key: str = ""
    operator: TolerationOperator = TolerationOperator.EQUAL
    value: str = ""
    effect: Optional[TaintEffect] = None  # None = all effects
    # NoExecute grace period (v1.Toleration.TolerationSeconds; None = forever)
    toleration_seconds: Optional[int] = None

    def tolerates(self, taint: Taint) -> bool:
        if self.effect is not None and self.effect != taint.effect:
            return False
        if self.key != "" and self.key != taint.key:
            return False
        op = TolerationOperator(self.operator)
        if op == TolerationOperator.EXISTS:
            return True
        return self.value == taint.value


# ---------------------------------------------------------------------------
# Volumes
# ---------------------------------------------------------------------------


class VolumeKind(str, enum.Enum):
    """The volume-source kinds the scheduler's volume predicates read
    (reference: predicates.go:128-177 isVolumeConflict + the EBS/GCEPD/
    AzureDisk VolumeFilters at predicates.go:324-374). Other sources
    (EmptyDir, ConfigMap, Secret, HostPath, NFS, ...) are scheduling-inert
    and collapse to OTHER."""

    GCE_PD = "GCEPersistentDisk"
    AWS_EBS = "AWSElasticBlockStore"
    RBD = "RBD"
    ISCSI = "ISCSI"
    AZURE_DISK = "AzureDisk"
    PVC = "PersistentVolumeClaim"
    # scheduling-inert but authz-relevant: the node authorizer only grants a
    # kubelet access to secrets/configmaps referenced by pods bound to it
    # (plugin/pkg/auth/authorizer/node/node_authorizer.go)
    SECRET = "Secret"
    CONFIG_MAP = "ConfigMap"
    OTHER = "Other"


@dataclass
class Volume:
    """One pod-spec volume, reduced to scheduler-relevant identity fields.

    volume_id carries the per-kind identity: PDName (GCE), VolumeID (EBS),
    DiskName (AzureDisk), IQN (ISCSI), claim name (PVC). RBD identity is
    (any shared monitor, pool, image) — predicates.go:163-172."""

    name: str = ""
    kind: VolumeKind = VolumeKind.OTHER
    volume_id: str = ""
    read_only: bool = False
    monitors: List[str] = field(default_factory=list)  # RBD CephMonitors
    pool: str = ""  # RBD RBDPool
    image: str = ""  # RBD RBDImage
    # concrete source for scheduling-inert kinds (OTHER collapses EmptyDir/
    # HostPath/NFS/DownwardAPI/...) — the volume plugin layer
    # (volumes/plugins.py) selects its driver by this, the way
    # pkg/volume/plugins.go FindPluginBySpec switches on the populated
    # VolumeSource member
    driver: str = ""


# PV node-affinity alpha annotation — v1.AlphaStorageNodeAffinityAnnotation
# (staging/src/k8s.io/api/core/v1/types.go; read by
# pkg/api/v1/helper/helpers.go:418 GetStorageNodeAffinityFromAnnotation)
ALPHA_STORAGE_NODE_AFFINITY_ANNOTATION = \
    "volume.alpha.kubernetes.io/node-affinity"


@dataclass
class PersistentVolume:
    """Cluster-scoped PV, reduced to what VolumeZone / MaxPDVolumeCount /
    VolumeNode read: zone labels, the backing source, and (alpha) node
    affinity (reference: predicates.go:376-474, pkg/volume/util/util.go:193
    CheckNodeAffinity)."""

    name: str
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    capacity: int = 0  # bytes (spec.capacity.storage; PV binder matching)
    access_modes: List[str] = field(default_factory=list)
    source: Volume = field(default_factory=Volume)
    # RequiredDuringScheduling node-selector terms; unlike pod node affinity
    # these are ANDed (util.go:202-214 loops ALL terms, each must match)
    node_affinity_terms: Optional[List["NodeSelectorTerm"]] = None
    resource_version: int = 0


@dataclass
class PersistentVolumeClaim:
    """Namespaced PVC: binds a pod volume to a PV by name
    (pvc.Spec.VolumeName — predicates.go:253-262)."""

    name: str
    namespace: str = "default"
    volume_name: str = ""  # bound PV name; empty = unbound
    capacity: int = 0  # requested bytes (spec.resources.requests.storage)
    access_modes: List[str] = field(default_factory=list)
    # class selection rides the v1.7 beta annotation
    # (volume.beta.kubernetes.io/storage-class), set by the user or the
    # StorageClassDefault admission plugin
    annotations: Dict[str, str] = field(default_factory=dict)
    resource_version: int = 0


# ---------------------------------------------------------------------------
# Pod
# ---------------------------------------------------------------------------


@dataclass
class ContainerPort:
    host_port: int = 0
    container_port: int = 0
    protocol: str = "TCP"


@dataclass
class Probe:
    """v1.Probe reduced to the fields the kubelet's prober reads
    (reference: pkg/kubelet/prober/prober.go + worker.go). `kind` is the
    handler type; outcomes in the hollow runtime are driven by pod
    annotations (nodes/kubelet.py), the way kubemark fakes the runtime."""

    kind: str = "exec"  # exec | httpGet | tcpSocket
    initial_delay_s: float = 0.0  # InitialDelaySeconds
    period_s: float = 10.0  # PeriodSeconds
    failure_threshold: int = 3  # FailureThreshold (worker.go)
    success_threshold: int = 1  # SuccessThreshold


@dataclass
class SecurityContext:
    """v1.SecurityContext (container-level), reduced to the fields PSP and
    the kubelet's securitycontext provider read (pkg/securitycontext/,
    pkg/apis/extensions PSP validation)."""

    privileged: Optional[bool] = None
    run_as_user: Optional[int] = None
    run_as_non_root: Optional[bool] = None
    read_only_root_filesystem: Optional[bool] = None


@dataclass
class PodSecurityContext:
    """v1.PodSecurityContext: pod-wide defaults containers inherit (only
    the fields a strategy actually enforces; FSGroup/SupplementalGroups
    strategies are not modeled)."""

    run_as_user: Optional[int] = None
    run_as_non_root: Optional[bool] = None


@dataclass
class Container:
    name: str = ""
    image: str = ""
    # None values mean "not specified" (relevant for nonzero-request defaults:
    # priorities/util/non_zero.go distinguishes unset from explicit zero).
    requests: Dict[str, int] = field(default_factory=dict)
    limits: Dict[str, int] = field(default_factory=dict)
    ports: List[ContainerPort] = field(default_factory=list)
    liveness_probe: Optional[Probe] = None
    readiness_probe: Optional[Probe] = None
    security_context: Optional[SecurityContext] = None


@dataclass
class Pod:
    name: str
    namespace: str = "default"
    uid: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    containers: List[Container] = field(default_factory=list)
    volumes: List[Volume] = field(default_factory=list)
    node_name: str = ""  # spec.nodeName; non-empty once bound
    node_selector: Dict[str, str] = field(default_factory=dict)
    affinity: Optional[Affinity] = None
    tolerations: List[Toleration] = field(default_factory=list)
    scheduler_name: str = "default-scheduler"
    priority: int = 0
    priority_class: str = ""
    # PodStatus.Phase (v1.PodPhase): Pending | Running | Succeeded | Failed.
    # Set by the node agent (models/hollow kubelet) after bind; controllers
    # and endpoints read it.
    phase: str = "Pending"
    restart_policy: str = "Always"  # Always | OnFailure | Never
    # PodCondition[Ready] (status manager): gates Endpoints membership.
    # A Running pod with no readiness probe is ready (prober results_manager
    # defaults); the kubelet flips this from probe outcomes.
    ready: bool = True
    restart_count: int = 0  # sum of ContainerStatus.RestartCount
    host_network: bool = False  # spec.hostNetwork (PSP HostNetwork check)
    security_context: Optional[PodSecurityContext] = None
    resource_version: int = 0
    owner_kind: str = ""  # controllerRef: equivalence classes, spreading,
    owner_name: str = ""  # NodePreferAvoidPods
    owner_uid: str = ""
    deleted: bool = False  # DeletionTimestamp != nil (spreading skips these)

    def key(self) -> str:
        # memoized: the drain hot path calls key() ~7x per pod per round
        # (queue, cache, metrics bookkeeping). Not a dataclass field, so
        # dataclasses.replace() never copies it; shallow queue-admission
        # copies (scheduler._queue_copy) DO carry it deliberately —
        # name/namespace are identity and never mutated in place, so the
        # memo cannot go stale across the hop.
        k = self.__dict__.get("_key")
        if k is None:
            k = self.namespace + "/" + self.name
            self.__dict__["_key"] = k
        return k

    def has_pod_affinity(self) -> bool:
        """Any pod (anti-)affinity term, required or preferred. The ONE
        definition behind both the cache's aff_seq bumps and the engine's
        encoding-staleness accounting (ops/affinity._has_affinity) — the
        two counters must agree pod-for-pod or encoding reuse either
        thrashes or trusts stale topology arrays."""
        a = self.affinity
        return a is not None and (a.pod_affinity is not None
                                  or a.pod_anti_affinity is not None)

    def resource_request(self) -> Resource:
        """Sum of container requests — GetResourceRequest
        (reference: predicates.go:478 computePodResourceRequest; init
        containers take elementwise max, not modeled yet)."""
        out = Resource()
        for c in self.containers:
            out.milli_cpu += c.requests.get("cpu", 0)
            out.memory += c.requests.get("memory", 0)
            out.nvidia_gpu += c.requests.get("nvidia.com/gpu", 0)
            out.storage_scratch += c.requests.get("storage.kubernetes.io/scratch", 0)
            out.storage_overlay += c.requests.get("storage.kubernetes.io/overlay", 0)
            for k, v in c.requests.items():
                if k not in ("cpu", "memory", "nvidia.com/gpu",
                             "storage.kubernetes.io/scratch",
                             "storage.kubernetes.io/overlay"):
                    out.extended[k] = out.extended.get(k, 0) + v
        return out

    def nonzero_request(self) -> Tuple[int, int]:
        """(milli_cpu, memory) with per-container defaults for unset requests —
        reference: priorities/util/non_zero.go:36-50 (unset ≠ explicit zero)."""
        cpu = 0
        mem = 0
        for c in self.containers:
            cpu += c.requests["cpu"] if "cpu" in c.requests else DEFAULT_MILLI_CPU_REQUEST
            mem += c.requests["memory"] if "memory" in c.requests else DEFAULT_MEMORY_REQUEST
        return cpu, mem

    def used_ports(self) -> List[int]:
        """Host ports requested, deduplicated — schedutil.GetUsedPorts returns
        a map (reference: plugin/pkg/scheduler/util/utils.go), so duplicates
        collapse; dedup also keeps per-word port bits distinct for the
        scatter-add commit in engine/batch.py."""
        return list(dict.fromkeys(
            p.host_port for c in self.containers for p in c.ports if p.host_port != 0))

    def is_best_effort(self) -> bool:
        """True when no container has any request or limit — v1qos.GetPodQOS
        BestEffort case (reference: pkg/api/v1/helper/qos/qos.go)."""
        for c in self.containers:
            if c.requests or c.limits:
                return False
        return True


# ---------------------------------------------------------------------------
# Node
# ---------------------------------------------------------------------------


class ConditionStatus(str, enum.Enum):
    TRUE = "True"
    FALSE = "False"
    UNKNOWN = "Unknown"


@dataclass
class NodeCondition:
    type: str  # Ready | MemoryPressure | DiskPressure | OutOfDisk | NetworkUnavailable
    status: ConditionStatus = ConditionStatus.FALSE


@dataclass
class ContainerImage:
    names: List[str] = field(default_factory=list)
    size_bytes: int = 0


@dataclass
class Node:
    name: str
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    allocatable: Resource = field(default_factory=Resource)
    # status.capacity when it differs from allocatable: the kubelet's
    # node-allocatable reservation (kube/system-reserved; pkg/kubelet/cm/
    # node_container_manager.go) publishes capacity - reserved as
    # allocatable. None = no reservation (capacity == allocatable).
    capacity: Optional[Resource] = None
    allowed_pod_number: int = 110
    taints: List[Taint] = field(default_factory=list)
    unschedulable: bool = False
    pod_cidr: str = ""  # spec.podCIDR (route controller, kubenet)
    conditions: List[NodeCondition] = field(default_factory=list)
    images: List[ContainerImage] = field(default_factory=list)
    # LastHeartbeatTime of the Ready condition (v1.NodeCondition) — written by
    # the kubelet status loop, read by the node lifecycle controller's
    # monitorNodeStatus (pkg/controller/node/node_controller.go:523)
    heartbeat: float = 0.0
    resource_version: int = 0

    def condition(self, ctype: str) -> ConditionStatus:
        for c in self.conditions:
            if c.type == ctype:
                return ConditionStatus(c.status)
        return ConditionStatus.UNKNOWN

    def is_ready(self) -> bool:
        """CheckNodeConditionPredicate truth (reference: predicates.go:1306-1337):
        Ready==True, OutOfDisk!=True-ish (must be False), NetworkUnavailable
        must be False, and not Unschedulable."""
        ok = True
        for c in self.conditions:
            if c.type == "Ready" and c.status != ConditionStatus.TRUE:
                ok = False
            elif c.type == "OutOfDisk" and c.status != ConditionStatus.FALSE:
                ok = False
            elif c.type == "NetworkUnavailable" and c.status != ConditionStatus.FALSE:
                ok = False
        if self.unschedulable:
            ok = False
        return ok


# ---------------------------------------------------------------------------
# Binding / events
# ---------------------------------------------------------------------------


@dataclass
class WorkloadObject:
    """Owner-ish object for SelectorSpreadPriority / ServiceAffinity: a
    Service, ReplicationController, ReplicaSet or StatefulSet reduced to the
    fields the scheduler reads — a namespaced label selector
    (reference: selector_spreading.go:59-85 getSelectors; algorithm listers
    GetPodServices/GetPodControllers/GetPodReplicaSets/GetPodStatefulSets).
    Services/RCs use map-equality selectors; RS/SS use LabelSelector."""

    kind: str  # Service | ReplicationController | ReplicaSet | StatefulSet
    name: str
    namespace: str = "default"
    match_labels: Dict[str, str] = field(default_factory=dict)
    match_expressions: List[SelectorRequirement] = field(default_factory=list)
    resource_version: int = 0

    def selects(self, pod: "Pod") -> bool:
        if pod.namespace != self.namespace:
            return False
        if not self.match_labels and not self.match_expressions:
            return False  # nil/empty selector objects are skipped by listers
        for k, v in self.match_labels.items():
            if pod.labels.get(k) != v:
                return False
        return all(r.matches_labels(pod.labels) for r in self.match_expressions)


@dataclass
class Binding:
    """POST pods/<name>/binding payload — sets pod.spec.nodeName atomically
    (reference: pkg/registry/core/pod/storage/storage.go:128 BindingREST)."""

    pod_name: str
    pod_namespace: str
    pod_uid: str
    node_name: str


@dataclass
class Event:
    """tools/record-style event (reference: scheduler.go:174,248 emits
    Scheduled / FailedScheduling)."""

    object_key: str
    reason: str
    message: str
    type: str = "Normal"


def make_pod(
    name: str,
    namespace: str = "default",
    cpu: Optional[int] = None,
    memory: Optional[int] = None,
    gpu: Optional[int] = None,
    labels: Optional[Dict[str, str]] = None,
    node_selector: Optional[Dict[str, str]] = None,
    tolerations: Optional[List[Toleration]] = None,
    affinity: Optional[Affinity] = None,
    ports: Optional[List[int]] = None,
    node_name: str = "",
    owner: Tuple[str, str] = ("", ""),
    extended: Optional[Dict[str, int]] = None,
    volumes: Optional[List[Volume]] = None,
) -> Pod:
    """Test/bench convenience constructor (one container)."""
    requests: Dict[str, int] = {}
    if cpu is not None:
        requests["cpu"] = cpu
    if memory is not None:
        requests["memory"] = memory
    if gpu is not None:
        requests["nvidia.com/gpu"] = gpu
    if extended:
        requests.update(extended)
    container = Container(
        name="c0",
        requests=requests,
        ports=[ContainerPort(host_port=p) for p in (ports or [])],
    )
    return Pod(
        name=name,
        namespace=namespace,
        uid=namespace + "/" + name,
        labels=labels or {},
        containers=[container],
        volumes=volumes or [],
        node_selector=node_selector or {},
        tolerations=tolerations or [],
        affinity=affinity,
        node_name=node_name,
        owner_kind=owner[0],
        owner_name=owner[1],
    )


def make_node(
    name: str,
    cpu: int = 4000,
    memory: int = 32 * 1024 ** 3,
    pods: int = 110,
    gpu: int = 0,
    labels: Optional[Dict[str, str]] = None,
    taints: Optional[List[Taint]] = None,
    ready: bool = True,
    unschedulable: bool = False,
    extended: Optional[Dict[str, int]] = None,
) -> Node:
    """Bench node shape defaults match scheduler_perf
    (reference: test/integration/scheduler_perf/scheduler_test.go:49-68:
    4 CPU / 32Gi / 110 pods)."""
    return Node(
        name=name,
        labels=labels or {},
        allocatable=Resource(
            milli_cpu=cpu, memory=memory, nvidia_gpu=gpu, extended=dict(extended or {})
        ),
        allowed_pod_number=pods,
        taints=taints or [],
        unschedulable=unschedulable,
        conditions=[
            NodeCondition("Ready", ConditionStatus.TRUE if ready else ConditionStatus.FALSE),
            NodeCondition("MemoryPressure", ConditionStatus.FALSE),
            NodeCondition("DiskPressure", ConditionStatus.FALSE),
            NodeCondition("OutOfDisk", ConditionStatus.FALSE),
            NodeCondition("NetworkUnavailable", ConditionStatus.FALSE),
        ],
    )
