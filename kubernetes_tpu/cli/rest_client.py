"""HTTP client with the ApiServer method surface — what client-go's
RESTClient is to the reference (staging/src/k8s.io/client-go/rest): verbs
over the REST layout served by server/rest_http.py, so Ktctl and the
controllers can run out-of-process."""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any, Optional, Tuple

from kubernetes_tpu.api import wire
from kubernetes_tpu.api.cluster import Eviction
from kubernetes_tpu.api.types import Binding
from kubernetes_tpu.server.apiserver import KIND_INFO
from kubernetes_tpu.server.apiserver_lite import Conflict, NotFound, WatchEvent


class HttpError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(f"{code}: {message}")
        self.code = code


class RestClient:
    def __init__(self, base: str, token: str = ""):
        self.base = base.rstrip("/")
        self.token = token
        self._discovery_cache = None

    # ------------------------------------------------------------ plumbing

    def discovery(self) -> dict:
        """GET /apis — the discovery document (built-ins + CRDs +
        aggregated groups); cached per client like client-go's
        CachedDiscoveryClient."""
        if self._discovery_cache is None:
            self._discovery_cache = self._do("GET", self.base + "/apis")
        return self._discovery_cache

    def openapi(self) -> dict:
        """GET /openapi/v2 — the server-published OpenAPI document
        (ktctl explain's remote source)."""
        return self._do("GET", self.base + "/openapi/v2")

    def _url(self, kind: str, namespace: str, name: str = "",
             sub: str = "") -> str:
        if kind in KIND_INFO:
            resource, cluster = KIND_INFO[kind]
            path = "/api/v1"
        else:
            # CRD-defined kind: route through the group path
            # /apis/{group}/{version}/... per the discovery doc
            row = next((r for r in self.discovery()["resources"]
                        if r["kind"] == kind and r.get("group")), None)
            if row is None:
                raise NotFound(f"unknown kind {kind!r}")
            resource, cluster = row["name"], not row["namespaced"]
            path = f"/apis/{row['group']}/{row['version']}"
        if namespace and not cluster:
            path += f"/namespaces/{namespace}"
        path += f"/{resource}"
        if name:
            path += f"/{name}"
        if sub:
            path += f"/{sub}"
        return self.base + path

    def _do(self, method: str, url: str, body: Optional[dict] = None):
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Content-Type", "application/json")
        if self.token:
            req.add_header("Authorization", "Bearer " + self.token)
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            payload = json.loads(e.read() or b"{}")
            msg = payload.get("message", str(e))
            if e.code == 404:
                raise NotFound(msg) from None
            if e.code == 409:
                raise Conflict(msg) from None
            raise HttpError(e.code, msg) from None

    # --------------------------------------------------------------- verbs

    def get(self, kind: str, namespace: str, name: str) -> Any:
        return wire.decode_any(
            self._do("GET", self._url(kind, namespace, name)), kind=kind)

    def list(self, kind: str, field_selector: str = "",
             namespace: str = "") -> Tuple[list, int]:
        url = self._url(kind, namespace)
        if field_selector:
            from urllib.parse import quote
            url += "?fieldSelector=" + quote(field_selector)
        out = self._do("GET", url)
        objs = [wire.decode_any(item, kind=kind) for item in out["items"]]
        return objs, out.get("resourceVersion", 0)

    def create(self, kind: str, obj: Any) -> int:
        ns = getattr(obj, "namespace", "")
        out = self._do("POST", self._url(kind, ns),
                       wire.encode(obj, kind=kind))
        if kind == "CustomResourceDefinition":
            # the served-resource set changed; re-discover on next use
            self._discovery_cache = None
        return out.get("resourceVersion", 0)

    def update(self, kind: str, obj: Any,
               expect_rv: Optional[int] = None) -> int:
        ns = getattr(obj, "namespace", "")
        url = self._url(kind, ns, obj.name)
        if expect_rv is not None:
            url += f"?resourceVersion={expect_rv}"  # CAS precondition
        out = self._do("PUT", url, wire.encode(obj, kind=kind))
        if kind == "CustomResourceDefinition":
            self._discovery_cache = None
        return out.get("resourceVersion", 0)

    def update_status(self, kind: str, obj: Any) -> int:
        ns = getattr(obj, "namespace", "")
        out = self._do("PUT", self._url(kind, ns, obj.name, sub="status"),
                       wire.encode(obj, kind=kind))
        return out.get("resourceVersion", 0)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self._do("DELETE", self._url(kind, namespace, name))
        if kind == "CustomResourceDefinition":
            self._discovery_cache = None

    def bind(self, binding: Binding) -> int:
        out = self._do("POST",
                       self._url("Pod", binding.pod_namespace,
                                 binding.pod_name, sub="binding"),
                       {"pod_name": binding.pod_name,
                        "pod_uid": binding.pod_uid,
                        "node_name": binding.node_name})
        return out.get("resourceVersion", 0)

    def evict(self, ev: Eviction) -> None:
        self._do("POST", self._url("Pod", ev.namespace, ev.pod_name,
                                   sub="eviction"), {})

    def scale(self, kind: str, namespace: str, name: str,
              replicas: Optional[int] = None) -> int:
        url = self._url(kind, namespace, name, sub="scale")
        if replicas is None:
            return self._do("GET", url)["replicas"]
        return self._do("PUT", url, {"replicas": replicas})["replicas"]

    def watch_since(self, kinds, from_rv: int, timeout=None):
        res = []
        for k in kinds:
            if k in KIND_INFO:
                res.append(KIND_INFO[k][0])
                continue
            # CRD-defined kind: resolve through discovery; dropping it
            # silently would make the server fall back to ALL kinds
            row = next((r for r in self.discovery()["resources"]
                        if r["kind"] == k), None)
            if row is None:
                raise NotFound(f"unknown kind {k!r}")
            res.append(row["name"])
        q = "&".join(["resourceVersion=" + str(from_rv)]
                     + [f"resource={r}" for r in res]
                     + ([f"timeout={timeout}"] if timeout else []))
        out = self._do("GET", self.base + "/api/v1/watch?" + q)
        return [WatchEvent(e["type"], e["kind"],
                           wire.decode_any(e["object"], kind=e["kind"]),
                           e["rv"]) for e in out]

    def healthz(self) -> dict:
        return self._do("GET", self.base + "/healthz")

    def version(self) -> dict:
        return self._do("GET", self.base + "/version")
