"""ktctl: the kubectl-equivalent CLI.

Command palette mirrors pkg/kubectl/cmd/cmd.go NewKubectlCommand's verbs that
operate on this control plane (reference: pkg/kubectl, 69k LoC — resource
builder in pkg/kubectl/resource, printers in pkg/printers):

  get | describe | create -f | apply -f | delete | scale | label | annotate |
  taint | cordon | uncordon | drain | rollout (status|history|undo) |
  top node | api-resources | version

Resource aliasing matches kubectl's short names (po, no, svc, rs, rc,
deploy, sts, ds, ns, pv, pvc, quota, sa, cm, pdb). Output: table (default),
-o wide | json | yaml | name. The backend is either an in-process ApiServer
or a RestServer URL (--server) — both expose the same verbs, like kubectl
against the secure/insecure ports.

`apply` is kubectl's full THREE-way strategic merge
(pkg/kubectl/cmd/apply.go:658, patch.go CreateThreeWayMergePatch): the
patch combines deletions from (last-applied, manifest) with
additions/updates from (live, manifest), played onto the live object —
manifest-dropped fields are pruned, live drift on manifest-specified
fields is reverted, and controller-owned fields survive untouched
(cli/strategicpatch.py; `diff` previews the same merge)."""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, List, Optional, Sequence

import yaml

from kubernetes_tpu.api import wire
from kubernetes_tpu.api.cluster import Eviction
from kubernetes_tpu.api.types import Node, Pod, Taint, TaintEffect
from kubernetes_tpu.server.apiserver import ApiServer, KIND_INFO

LAST_APPLIED = "kubectl.kubernetes.io/last-applied-configuration"

_ABSENT = object()  # _project_to_raw sentinel (None is a real YAML value)

ALIASES = {
    "po": "pods", "pod": "pods",
    "no": "nodes", "node": "nodes",
    "svc": "services", "service": "services",
    "rs": "replicasets", "replicaset": "replicasets",
    "rc": "replicationcontrollers",
    "deploy": "deployments", "deployment": "deployments",
    "sts": "statefulsets", "statefulset": "statefulsets",
    "ds": "daemonsets", "daemonset": "daemonsets",
    "ns": "namespaces", "namespace": "namespaces",
    "pv": "persistentvolumes", "pvc": "persistentvolumeclaims",
    "quota": "resourcequotas", "sa": "serviceaccounts",
    "cm": "configmaps", "secret": "secrets",
    "pdb": "poddisruptionbudgets", "ep": "endpoints",
    "job": "jobs", "limits": "limitranges",
    "ev": "events", "event": "events",
}
RESOURCE_TO_KIND = {res: kind for kind, (res, _) in KIND_INFO.items()}


def resolve_kind(arg: str) -> str:
    res = ALIASES.get(arg.lower(), arg.lower())
    kind = RESOURCE_TO_KIND.get(res)
    if kind is None:
        # allow exact kind names too
        for k in KIND_INFO:
            if k.lower() == arg.lower():
                return k
        raise SystemExit(f"error: the server doesn't have a resource type "
                         f"{arg!r}")
    return kind


def kind_plural(kind: str) -> str:
    return KIND_INFO.get(kind, (kind.lower() + "s", False))[0]


# ---------------------------------------------------------------- printers

def _pod_row(p: Pod) -> List[str]:
    ready = "1/1" if p.phase == "Running" else "0/1"
    return [p.name, ready, p.phase, p.node_name or "<none>"]


def _node_row(n: Node) -> List[str]:
    status = "Ready" if n.is_ready() else "NotReady"
    if n.unschedulable:
        status += ",SchedulingDisabled"
    return [n.name, status, str(n.allocatable.milli_cpu) + "m",
            str(n.allocatable.memory)]


HEADERS = {
    "Pod": ["NAME", "READY", "STATUS", "NODE"],
    "Node": ["NAME", "STATUS", "CPU", "MEMORY"],
}


def table(kind: str, objs: Sequence[Any], wide: bool = False) -> str:
    if kind == "Pod":
        rows = [_pod_row(o) for o in objs]
        headers = HEADERS["Pod"]
    elif kind == "Node":
        rows = [_node_row(o) for o in objs]
        headers = HEADERS["Node"]
    elif hasattr(objs[0] if objs else None, "replicas"):
        headers = ["NAME", "DESIRED", "READY"]
        rows = [[o.name, str(getattr(o, "replicas", "")),
                 str(getattr(o, "ready_replicas", ""))] for o in objs]
    else:
        headers = ["NAME", "NAMESPACE"]
        rows = [[getattr(o, "name", ""), getattr(o, "namespace", "")]
                for o in objs]
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
              for i, h in enumerate(headers)]
    lines = ["  ".join(h.ljust(w) for h, w in zip(headers, widths))]
    for r in rows:
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    return "\n".join(lines)


def jsonpath_get(doc: Any, path: str) -> List[Any]:
    """The jsonpath subset kubectl output uses most (pkg/util/jsonpath):
    dotted fields, [N] indexing, [*] fan-out — '{.items[*].name}'.
    Returns the list of leaf matches."""
    path = path.strip()
    if path.startswith("{") and path.endswith("}"):
        path = path[1:-1]
    cur = [doc]
    for raw in filter(None, path.replace("]", "").split(".")):
        # a segment may carry an index suffix: "items[*" / "conditions[0"
        parts = raw.split("[")
        fieldname, indices = parts[0], parts[1:]
        nxt: List[Any] = []
        for c in cur:
            if fieldname:
                if not isinstance(c, dict) or fieldname not in c:
                    continue
                c = c[fieldname]
            vals = [c]
            for idx in indices:
                stepped: List[Any] = []
                for v in vals:
                    if not isinstance(v, list):
                        continue
                    if idx == "*":
                        stepped.extend(v)
                    else:
                        try:
                            i = int(idx)
                        except ValueError:
                            # filters/slices are outside the subset —
                            # fail like every other bad CLI input
                            raise SystemExit(
                                f"error: unsupported jsonpath "
                                f"expression [{idx}] (only [N] and [*] "
                                f"indexing is supported)") from None
                        if -len(v) <= i < len(v):
                            stepped.append(v[i])
                vals = stepped
            nxt.extend(vals)
        cur = nxt
    return cur


def _fmt_cell(v: Any) -> str:
    if v is None:
        return "<none>"
    if isinstance(v, (dict, list)):
        return json.dumps(v, default=str)
    return str(v)


def render(kind: str, objs: Sequence[Any], output: str,
           plural: str = "", sort_by: str = "") -> str:
    encoded = None
    if sort_by or output.startswith(("custom-columns=", "jsonpath=")):
        encoded = [wire.encode(o, kind=kind) for o in objs]
    if sort_by:
        # kubectl --sort-by: a jsonpath over each row (pkg/kubectl/
        # sorting_printer.go); unkeyed rows sort first, numeric keys
        # compare numerically (900 before 1000, not lexicographically)
        def keyf(pair):
            hits = jsonpath_get(pair[0], sort_by)
            if not hits:
                return (0, 0, 0.0, "")
            v = hits[0]
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                try:
                    return (1, 0, float(v), "")
                except (TypeError, ValueError):
                    return (1, 1, 0.0, str(v))
            return (1, 0, float(v), "")
        order = sorted(zip(encoded, objs), key=keyf)
        encoded = [e for e, _ in order]
        objs = [o for _, o in order]
    if output.startswith("custom-columns="):
        # NAME:.path,HEADER:.other.path (pkg/printers/customcolumn.go)
        cols = []
        for spec in output[len("custom-columns="):].split(","):
            header, _, path = spec.partition(":")
            cols.append((header, path))
        rows = [[_fmt_cell((jsonpath_get(e, p) or [None])[0])
                 for _h, p in cols] for e in encoded]
        headers = [h for h, _p in cols]
        widths = [max(len(h), *(len(r[i]) for r in rows)) if rows
                  else len(h) for i, h in enumerate(headers)]
        lines = ["  ".join(h.ljust(w) for h, w in zip(headers, widths))]
        lines += ["  ".join(c.ljust(w) for c, w in zip(r, widths))
                  for r in rows]
        return "\n".join(lines)
    if output.startswith("jsonpath="):
        # applied to the List document like kubectl ({.items[*].name})
        doc = {"kind": kind + "List", "items": encoded}
        hits = jsonpath_get(doc, output[len("jsonpath="):])
        return " ".join(_fmt_cell(h) for h in hits)
    if output == "json":
        return json.dumps(
            encoded if encoded is not None
            else [wire.encode(o, kind=kind) for o in objs], indent=2)
    if output == "yaml":
        return yaml.safe_dump(
            encoded if encoded is not None
            else [wire.encode(o, kind=kind) for o in objs])
    if output == "name":
        res = plural or kind_plural(kind)
        return "\n".join(f"{res}/{getattr(o, 'name', '')}" for o in objs)
    return table(kind, objs, wide=(output == "wide"))


def describe(kind: str, obj: Any) -> str:
    enc = wire.encode(obj, kind=kind)
    lines = [f"Name:       {enc.pop('name', '')}"]
    if "namespace" in enc:
        lines.append(f"Namespace:  {enc.pop('namespace')}")
    for k, v in enc.items():
        lines.append(f"{k}: {json.dumps(v, default=str)}"
                     if not isinstance(v, str) else f"{k}: {v}")
    return "\n".join(lines)


# --------------------------------------------------------------- the tool

class _BoundApi:
    """Binds a client credential onto every authenticated verb — the
    kubeconfig current-context: ktctl code stays credential-agnostic and
    the secure-port path just works (client-go's rest.Config analog)."""

    _CRED_VERBS = frozenset({
        "create", "get", "list", "update", "delete", "scale", "evict",
        "bind", "bind_many", "update_status", "watch_since",
        "finalize_namespace"})

    def __init__(self, api, cred):
        self._api = api
        self._cred = cred

    def __getattr__(self, name):
        fn = getattr(self._api, name)
        if name in self._CRED_VERBS:
            import functools
            return functools.partial(fn, cred=self._cred)
        return fn


class Ktctl:
    """The CLI against an in-process ApiServer (tests, single binary) or a
    remote REST endpoint (via RestClient below)."""

    def __init__(self, api: ApiServer, out=None, federation=None,
                 federation_contexts=None, cred=None,
                 kubeconfig: Optional[str] = None, kubelets=None):
        # `kubelets`: node name -> kubelet API base URL (nodes/
        # kubelet_server.py) or in-process HollowKubelet — the routing
        # table `logs`/`exec` use, the way kubectl reaches kubelets
        # through the apiserver proxy
        self.kubelets = kubelets or {}
        if kubeconfig is not None:
            # a ktadm-written kubeconfig (cli/ktadm.py phase_kubeconfig):
            # carry its identity record as the client credential
            from kubernetes_tpu.auth.authn import Credential
            with open(kubeconfig) as f:
                cfg = json.load(f)
            cred = Credential(cert=cfg["cert"])
        # only the in-process ApiServer takes per-call credentials; a
        # RestClient authenticates at the transport (its bearer token)
        self.api = api if cred is None or not isinstance(api, ApiServer) \
            else _BoundApi(api, cred)
        self.out = out if out is not None else sys.stdout
        # kubefed mode (cmd_federate): `federation` is a
        # FederationControlPlane, `federation_contexts` maps cluster name ->
        # member ApiServer (the kubeconfig-contexts analog kubefed joins by)
        self.federation = federation
        self.federation_contexts = federation_contexts or {}

    def _print(self, s: str) -> None:
        self.out.write(s + "\n")

    # each method returns the text it printed (handy for tests)

    def run(self, argv: Sequence[str]) -> int:
        if not argv:
            self._print("ktctl controls the kubernetes_tpu control plane")
            return 0
        cmd, *rest = argv
        fn = getattr(self, "cmd_" + cmd.replace("-", "_"), None)
        if fn is None:
            self._print(f"error: unknown command {cmd!r}")
            return 1
        # kubectl --as / --as-group: rebind this invocation's credential
        # with impersonation headers (the server's impersonation filter
        # authorizes the REAL user for the impersonate verb)
        restore = None
        try:
            has_as = any(a == "--as" or a.startswith("--as=")
                         or a == "--as-group"
                         or a.startswith("--as-group=") for a in rest)
            if cmd != "auth" and has_as:
                # (`auth can-i --as` consumes the flag itself — it runs a
                # SubjectAccessReview about the target, not as them)
                if not isinstance(self.api, _BoundApi):
                    # silently running at the caller's full privilege
                    # would make "can X do this?" probes lie
                    raise SystemExit(
                        "error: --as requires an authenticated "
                        "in-process backend (credential-bound)")
                import dataclasses as _dc
                # normalize the equals form kubectl users routinely type
                # (--as=user) so it cannot slip past as an ordinary flag
                norm = []
                for a in rest:
                    if a.startswith("--as="):
                        norm += ["--as", a.split("=", 1)[1]]
                    elif a.startswith("--as-group="):
                        norm += ["--as-group", a.split("=", 1)[1]]
                    else:
                        norm.append(a)
                rest = norm
                as_user, as_groups = "", []
                while "--as" in rest:
                    i = rest.index("--as")
                    if i + 1 >= len(rest):
                        raise SystemExit(
                            "error: flag --as needs an argument")
                    as_user = rest[i + 1]
                    del rest[i:i + 2]
                while "--as-group" in rest:
                    i = rest.index("--as-group")
                    if i + 1 >= len(rest):
                        raise SystemExit(
                            "error: flag --as-group needs an argument")
                    as_groups.append(rest[i + 1])
                    del rest[i:i + 2]
                if as_groups and not as_user:
                    raise SystemExit(
                        "error: --as-group requires --as (kubectl "
                        "rejects group-only impersonation)")
                restore = self.api
                self.api = _BoundApi(restore._api, _dc.replace(
                    restore._cred, impersonate_user=as_user,
                    impersonate_groups=tuple(as_groups)))
            rc = fn(rest)
            # verbs with exit-code semantics beyond ok/error (diff's
            # "1 = differences found") return an int
            return rc if isinstance(rc, int) else 0
        except SystemExit as e:
            self._print(str(e))
            return 1
        finally:
            if restore is not None:
                self.api = restore

    # flags that never take a value (boolean presence flags)
    BOOL_FLAGS = frozenset({"all-namespaces", "watch", "wide", "force",
                            "ignore-daemonsets"})

    @classmethod
    def _flags(cls, args: List[str]) -> (List[str], Dict[str, str]):
        pos, flags = [], {}
        i = 0
        while i < len(args):
            a = args[i]
            if a.startswith("--"):
                if "=" in a:
                    k, _, v = a[2:].partition("=")
                    flags[k] = v
                elif a[2:] in cls.BOOL_FLAGS or i + 1 >= len(args) \
                        or args[i + 1].startswith("-"):
                    flags[a[2:]] = ""
                else:
                    flags[a[2:]] = args[i + 1]
                    i += 1
            elif a == "-n":
                flags["namespace"] = args[i + 1]
                i += 1
            elif a == "-o":
                flags["output"] = args[i + 1]
                i += 1
            elif a == "-f":
                flags["filename"] = args[i + 1]
                i += 1
            elif a == "-l":
                flags["selector"] = args[i + 1]
                i += 1
            elif a == "-p":
                flags["patch"] = args[i + 1]
                i += 1
            else:
                pos.append(a)
            i += 1
        return pos, flags

    # -- dynamic resource resolution (discovery-backed, CRDs included) ----

    def _discovery_resources(self) -> List[Dict[str, Any]]:
        try:
            return self.api.discovery().get("resources", [])
        except Exception:
            return []

    def _resolve_kind(self, arg: str) -> str:
        """Builtin aliases first, then the discovery doc — so
        `ktctl get tputopologies` (or a CRD short name) works as soon as
        the CRD is Established, like kubectl's RESTMapper over the
        discovery client."""
        try:
            return resolve_kind(arg)
        except SystemExit:
            low = arg.lower()
            res = ALIASES.get(low, low)
            for r in self._discovery_resources():
                if not r.get("group"):
                    continue
                if r["name"] == res or r["kind"].lower() == low or low in \
                        [s.lower() for s in r.get("shortNames", [])]:
                    return r["kind"]
            raise

    def _cluster_scoped(self, kind: str) -> bool:
        if kind in KIND_INFO:
            return KIND_INFO[kind][1]
        for r in self._discovery_resources():
            if r["kind"] == kind:
                return not r["namespaced"]
        return False

    def _plural(self, kind: str) -> str:
        """Resource name for output (`pods/x created`, `-o name`) — the
        discovery doc is authoritative for CRD kinds, so the printed name
        round-trips back into ktctl."""
        if kind in KIND_INFO:
            return KIND_INFO[kind][0]
        for r in self._discovery_resources():
            if r["kind"] == kind:
                return r["name"]
        return kind_plural(kind)

    def _objs_rv(self, kind: str, ns: str, name: str = "",
                 selector: str = "", field_selector: str = ""):
        """_objs plus the list resourceVersion — the watch path needs the
        rv of the SAME snapshot the table rendered, or events landing
        between two lists are lost."""
        objs = self._objs(kind, ns, name, selector, field_selector,
                          _rv_box=(box := []))
        return objs, (box[0] if box else 0)

    def _objs(self, kind: str, ns: str, name: str = "",
              selector: str = "", field_selector: str = "",
              _rv_box=None) -> List[Any]:
        if name:
            if selector or field_selector:
                # kubectl refuses a resource name combined with selectors
                # — silently ignoring the filter the user typed is worse
                raise SystemExit(
                    "error: selectors cannot be combined with a "
                    "resource name")
            return [self.api.get(kind, ns if not self._cluster_scoped(kind) else "",
                                 name)]
        from kubernetes_tpu.cli.rest_client import HttpError
        from kubernetes_tpu.server.apiserver import Invalid
        # field AND namespace selection run SERVER-side (the reference's
        # namespaced list endpoints scope the RBAC check too — a user
        # with only a namespaced Role must be able to `get pods -n ns`);
        # kwargs are passed only when set so a bare ApiServerLite backend
        # (kubefed's member clusters) keeps working
        kwargs = {}
        if field_selector:
            kwargs["field_selector"] = field_selector
        namespaced = not self._cluster_scoped(kind) and ns != "*"
        if namespaced:
            kwargs["namespace"] = ns
        if kwargs:
            # signature check, NOT try/except TypeError: a TypeError
            # raised inside a supporting backend must surface, not
            # silently retry with the user's filters stripped
            import inspect
            try:
                params = inspect.signature(self.api.list).parameters
                supported = all(k in params for k in kwargs)
            except (TypeError, ValueError):
                supported = False
            if not supported:
                kwargs = {}
        try:
            objs, rv = self.api.list(kind, **kwargs)
            if _rv_box is not None:
                _rv_box.append(rv)
        except (Invalid, HttpError) as e:
            raise SystemExit(f"error: {e}") from None
        if namespaced and "namespace" not in kwargs:
            objs = [o for o in objs if getattr(o, "namespace", "") == ns]
        if field_selector and "field_selector" not in kwargs:
            # fallback backend: apply the fields axis client-side so the
            # output is FILTERED either way, never silently unfiltered
            from kubernetes_tpu.api.fields import (
                FieldSelectorError,
                filter_objects,
                parse_field_selector,
            )
            try:
                objs = filter_objects(kind, objs,
                                      parse_field_selector(field_selector))
            except FieldSelectorError as e:
                raise SystemExit(f"error: {e}") from None
        if selector:
            want = dict(kv.split("=", 1) for kv in selector.split(",")
                        if "=" in kv)
            objs = [o for o in objs
                    if all(getattr(o, "labels", {}).get(k) == v
                           for k, v in want.items())]
        return objs

    def cmd_get(self, args):
        pos, flags = self._flags(args)
        if not pos:
            raise SystemExit("error: resource type required")
        kind = self._resolve_kind(pos[0])
        ns = flags.get("namespace", "default")
        if "all-namespaces" in flags:
            ns = "*"
        name = pos[1] if len(pos) > 1 else ""
        sel = flags.get("selector", "")
        fsel = flags.get("field-selector", "")
        output = flags.get("output", "table")
        objs, list_rv = self._objs_rv(kind, ns, name, sel, fsel)
        self._print(render(kind, objs, output,
                           plural=self._plural(kind),
                           sort_by=flags.get("sort-by", "")))
        if "watch" in flags:
            # kubectl get --watch: stream subsequent changes as rows
            # (cmd/get.go watch path), scoped by the SAME name/label/field
            # filters as the table and resumed from the table's own rv so
            # no intervening event is lost. Bounded by --watch-timeout
            # (default 2s) — the library/test harness cannot block
            # forever the way an interactive kubectl does.
            try:
                timeout = float(flags.get("watch-timeout") or 2.0)
            except ValueError:
                raise SystemExit(
                    f"error: invalid --watch-timeout "
                    f"{flags['watch-timeout']!r}") from None
            self._watch_loop(kind, ns, name, sel, fsel, output,
                             list_rv, timeout)

    def _event_matches(self, kind: str, obj, ns: str, name: str,
                       selector: str, field_selector: str) -> bool:
        if name and getattr(obj, "name", "") != name:
            return False
        if ns != "*" and not self._cluster_scoped(kind) \
                and getattr(obj, "namespace", "") != ns:
            return False
        if selector:
            want = dict(kv.split("=", 1) for kv in selector.split(",")
                        if "=" in kv)
            if not all(getattr(obj, "labels", {}).get(k) == v
                       for k, v in want.items()):
                return False
        if field_selector:
            from kubernetes_tpu.api.fields import (
                filter_objects,
                parse_field_selector,
            )
            if not filter_objects(kind, [obj],
                                  parse_field_selector(field_selector)):
                return False
        return True

    def _watch_loop(self, kind, ns, name, sel, fsel, output, rv,
                    timeout) -> None:
        import time as _time
        deadline = _time.monotonic() + timeout
        while True:
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                return
            try:
                evs = self.api.watch_since((kind,), rv,
                                           timeout=min(remaining, 0.25))
            except Exception as e:
                # HttpError in REST mode, TooOldResourceVersion on log
                # compaction: the CLI contract is error + exit 1
                raise SystemExit(
                    f"error: watch failed: {e} (relist and re-watch)"
                ) from None
            for ev in evs:
                rv = max(rv, ev.rv)
                if not self._event_matches(kind, ev.obj, ns, name, sel,
                                           fsel):
                    continue
                row = render(kind, [ev.obj], output,
                             plural=self._plural(kind))
                if output in ("table", "wide"):
                    row = row.splitlines()[-1]  # drop the repeated header
                self._print(f"{ev.type}\t{row}")

    def cmd_describe(self, args):
        pos, flags = self._flags(args)
        kind = self._resolve_kind(pos[0])
        ns = flags.get("namespace", "default")
        try:
            events, _ = self.api.list("Event")  # one fetch for all objects
        except Exception:
            events = []
        for obj in self._objs(kind, ns, pos[1] if len(pos) > 1 else ""):
            self._print(describe(kind, obj))
            # the Events section kubectl describe ends with
            # (pkg/printers/internalversion/describe.go DescribeEvents):
            # recorder convention — namespaced objects key as <ns>/<name>
            # (obj.key()), cluster-scoped ones by bare name (Node/PV
            # events would never match a "/name" key)
            key = obj.key() if hasattr(obj, "key") else (
                (getattr(obj, "namespace", "") + "/" + obj.name)
                if getattr(obj, "namespace", "") else obj.name)
            rows = [e for e in events
                    if getattr(e, "involved_key", "") == key
                    and getattr(e, "involved_kind", kind) == kind]
            if rows:
                self._print("Events:")
                self._print("  TYPE\tREASON\tCOUNT\tMESSAGE")
                for e in rows:
                    self._print(f"  {e.type}\t{e.reason}\t"
                                f"{getattr(e, 'count', 1)}\t{e.message}")

    def _load_manifests(self, flags) -> List[Any]:
        text = open(flags["filename"]).read() \
            if flags.get("filename", "-") != "-" else sys.stdin.read()
        docs = [d for d in yaml.safe_load_all(text) if d]
        return [wire.decode_any(d) for d in docs], docs

    def cmd_create(self, args):
        _, flags = self._flags(args)
        objs, raws = self._load_manifests(flags)
        for obj, raw in zip(objs, raws):
            kind = raw.get("kind")
            self.api.create(kind, obj)
            self._print(f"{self._plural(kind)}/{obj.name} created")

    # ---- the canonical manifest shape the merge machinery operates on.
    # Pod/Node use the serde metadata/spec shape — precisely the SPEC
    # surface, so a merge can never stomp status or server bookkeeping;
    # everything else uses the flat reflective wire shape. User manifests
    # in either accepted shape are normalized through decode->encode
    # before diffing, so 3-way inputs always agree on shape.

    def _canon_manifest(self, kind: str, obj) -> Dict[str, Any]:
        from kubernetes_tpu.api import serde
        if kind == "Pod":
            return serde.encode_pod(obj)
        if kind == "Node":
            return serde.encode_node(obj)
        return wire.encode(obj, kind)

    @staticmethod
    def _with_last_applied(canon: Dict[str, Any],
                           canon_txt: str) -> Dict[str, Any]:
        import copy as _copy
        out = _copy.deepcopy(canon)
        if "metadata" in out:
            out["metadata"].setdefault("annotations", {})[LAST_APPLIED] = \
                canon_txt
        elif isinstance(out.get("annotations"), dict) or \
                "annotations" not in out:
            out.setdefault("annotations", {})[LAST_APPLIED] = canon_txt
        return out

    # Node annotation keys the CONTROL PLANE owns (controllers write them
    # between a client's read and its update): survive apply/patch/edit
    # even when the user's manifest omits them. Everything else in
    # metadata.annotations is client-owned — the merged manifest is
    # authoritative, so a user-requested annotation change sticks.
    SERVER_OWNED_NODE_ANNOTATIONS = (
        "node.alpha.kubernetes.io/ttl",           # quota_sa TTL controller
        "volumes.kubernetes.io/attached",         # attach-detach controller
        "volumes.kubernetes.io/in-use",           # kubelet status sync — the
        # attach-detach controller's detach guard reads it (cloudctrl.py);
        # losing it to an apply could detach a still-mounted volume
    )

    def _decode_canon(self, kind: str, data: Dict[str, Any], cur):
        """Canonical manifest -> live object, restoring the status/server
        fields the spec-surface encoding doesn't carry (apply and patch
        never touch status — the reference's status-subresource split).
        Annotations are NOT wholesale-restored: the merge already computed
        them from (live, manifest), and clobbering that with the live map
        silently discarded every user-requested annotation change; only
        the server-owned keys above are re-added if the merge lost them."""
        new_obj = wire.decode_any(data, kind)
        if cur is not None:
            if kind == "Pod":
                new_obj.phase = cur.phase
                new_obj.ready = cur.ready
                new_obj.restart_count = cur.restart_count
            elif kind == "Node":
                new_obj.heartbeat = cur.heartbeat
                for k in self.SERVER_OWNED_NODE_ANNOTATIONS:
                    if k in cur.annotations and k not in new_obj.annotations:
                        new_obj.annotations[k] = cur.annotations[k]
            new_obj.resource_version = cur.resource_version
        return new_obj

    @staticmethod
    def _norm_key(k: str) -> str:
        return k.replace("_", "").replace("-", "").lower()

    def _project_to_raw(self, canon, raw):
        """Keep only the canonical keys the user's manifest actually wrote
        (tolerant of camelCase vs snake_case spelling, positional for
        lists, which decode preserves). The canonical shape is a
        decode->encode round trip, so it materializes DEFAULTS for every
        absent field; the drift-reverting delta half of the 3-way merge
        must not treat those as user intent — kubectl computes `modified`
        from the file bytes for exactly this reason
        (GetModifiedConfiguration).

        Shape-tolerant: decode_any accepts BOTH the metadata/spec nesting
        and the flat native shape, so the projection must not require the
        raw manifest to nest the same way the canonical encoding does — a
        flat-shape Pod manifest would otherwise project to an EMPTY delta
        and apply would silently drop every field update. Canonical
        metadata/spec levels match against the flat raw directly, and flat
        canonical keys also search raw's metadata/spec levels."""
        if isinstance(canon, dict) and isinstance(raw, dict):
            # lookup spaces: raw itself first, then its metadata/spec
            # levels (for flat-canon x nested-raw); first hit wins
            raw_by = {self._norm_key(k): v for k, v in raw.items()}
            for lvl in ("metadata", "spec"):
                sub = raw.get(lvl)
                if isinstance(sub, dict):
                    for k, v in sub.items():
                        raw_by.setdefault(self._norm_key(k), v)
            out = {}
            for k, v in canon.items():
                if k in ("metadata", "spec") and isinstance(v, dict) \
                        and not isinstance(raw.get(k), dict):
                    # nested-canon x flat-raw: the user's keys live at the
                    # raw top level — project the nesting against it
                    out[k] = self._project_to_raw(v, raw)
                    continue
                rv = raw_by.get(self._norm_key(k), _ABSENT)
                if rv is _ABSENT:
                    continue
                if isinstance(v, dict) and isinstance(rv, dict):
                    out[k] = self._project_to_raw(v, rv)
                elif isinstance(v, list) and isinstance(rv, list) \
                        and len(v) == len(rv):
                    out[k] = [self._project_to_raw(ci, ri)
                              if isinstance(ci, dict) and isinstance(ri, dict)
                              else ci
                              for ci, ri in zip(v, rv)]
                else:
                    out[k] = v
            return out
        return canon

    def _merge_preview(self, kind: str, obj, raw=None):
        """THE 3-way merge apply performs, shared by apply and diff so the
        preview can never drift from the write: returns (cur, cur_manifest,
        merged, canon_txt, changed). cur is None for would-create. Like
        kubectl, the modified object carries the new last-applied
        annotation INTO the diff — metadata.annotations is then never
        absent from `modified`, so dropping the user's annotations from a
        manifest prunes them per-key instead of nuking the whole map
        (controller-set keys survive). `raw` (the manifest as the user
        wrote it) narrows the drift-reverting delta half to
        manifest-specified fields (_project_to_raw)."""
        from kubernetes_tpu.cli import strategicpatch
        ns = getattr(obj, "namespace", "")
        canon_new = self._canon_manifest(kind, obj)
        canon_txt = json.dumps(canon_new, sort_keys=True)
        try:
            cur = self.api.get(
                kind, ns if not self._cluster_scoped(kind) else "",
                obj.name)
        except Exception:
            cur = None
        if cur is None:
            return None, None, None, canon_txt, True
        prev_txt = getattr(cur, "annotations", {}).get(LAST_APPLIED, "")
        prev = json.loads(prev_txt) if prev_txt else {}
        cur_manifest = self._canon_manifest(kind, cur)
        modified = self._with_last_applied(canon_new, canon_txt)
        delta_view = self._project_to_raw(canon_new, raw) \
            if raw is not None else None
        merged = strategicpatch.three_way_merge(prev, modified,
                                                cur_manifest,
                                                modified_for_delta=delta_view)
        changed = not (merged == cur_manifest and prev_txt == canon_txt)
        return cur, cur_manifest, merged, canon_txt, changed

    def cmd_apply(self, args):
        """kubectl apply: THREE-way strategic merge (apply.go:658) — the
        patch is computed from (last-applied, new manifest) and played
        onto the LIVE object, so manifest-removed fields/list items are
        pruned while controller-owned fields (an HPA's replicas, status,
        defaults) survive untouched."""
        _, flags = self._flags(args)
        objs, raws = self._load_manifests(flags)
        for obj, raw in zip(objs, raws):
            kind = raw.get("kind")
            cur, _cur_manifest, merged, canon_txt, changed = \
                self._merge_preview(kind, obj, raw=raw)
            if cur is None:
                if hasattr(obj, "annotations"):
                    obj.annotations[LAST_APPLIED] = canon_txt
                self.api.create(kind, obj)
                self._print(f"{self._plural(kind)}/{obj.name} created")
                continue
            if not changed:
                self._print(f"{self._plural(kind)}/{obj.name} unchanged")
                continue
            new_obj = self._decode_canon(kind, merged, cur)
            if hasattr(new_obj, "annotations"):
                new_obj.annotations[LAST_APPLIED] = canon_txt
            self.api.update(kind, new_obj)
            self._print(f"{self._plural(kind)}/{obj.name} configured")

    def cmd_diff(self, args):
        """kubectl diff -f FILE: show what apply WOULD change — the same
        3-way merge apply performs, rendered as a unified diff of the
        live object vs the merged result, without writing anything
        (kubectl cmd/diff.go's server-dry-run shape, computed with the
        strategic-merge machinery apply already uses). Exit code 1 when
        differences exist, 0 when clean — kubectl's contract."""
        import difflib

        _, flags = self._flags(args)
        objs, raws = self._load_manifests(flags)
        any_changed = False
        for obj, raw in zip(objs, raws):
            kind = raw.get("kind")
            cur, cur_manifest, merged, canon_txt, changed = \
                self._merge_preview(kind, obj, raw=raw)
            if cur is None:
                any_changed = True
                self._print(f"+ {self._plural(kind)}/{obj.name} "
                            f"(would be created)")
                continue
            if not changed:
                continue
            any_changed = True
            # render what apply will actually WRITE: the merge result plus
            # the refreshed last-applied stamp (apply sets it after decode,
            # outside the merge). Without it, a run where only last-applied
            # moves exits 1 with an EMPTY diff; kubectl renders the
            # annotation change in this case
            after_obj = self._with_last_applied(merged, canon_txt)
            before = json.dumps(cur_manifest, indent=2,
                                sort_keys=True).splitlines()
            after = json.dumps(after_obj, indent=2,
                               sort_keys=True).splitlines()
            for line in difflib.unified_diff(
                    before, after,
                    fromfile=f"live/{self._plural(kind)}/{obj.name}",
                    tofile=f"merged/{self._plural(kind)}/{obj.name}",
                    lineterm=""):
                self._print(line)
        return 1 if any_changed else 0

    def cmd_patch(self, args):
        """kubectl patch -p '<json>': server-state strategic merge patch
        (pkg/kubectl/cmd/patch.go, default --type=strategic): merge-keyed
        lists merge per item, null deletes a key, $patch: delete removes a
        keyed list item."""
        from kubernetes_tpu.cli import strategicpatch
        pos, flags = self._flags(args)
        if "patch" not in flags:
            raise SystemExit("error: -p / --patch is required")
        kind = self._resolve_kind(pos[0])
        ns = "" if self._cluster_scoped(kind) \
            else flags.get("namespace", "default")
        patch = json.loads(flags["patch"])
        cur = self.api.get(kind, ns, pos[1])
        # the patch follows the object's manifest shape (metadata/spec for
        # Pod/Node, flat for the rest) — same contract as apply manifests
        merged = strategicpatch.strategic_merge_patch(
            self._canon_manifest(kind, cur), patch)
        new_obj = self._decode_canon(kind, merged, cur)
        self.api.update(kind, new_obj)
        self._print(f"{self._plural(kind)}/{pos[1]} patched")

    def cmd_edit(self, args):
        """kubectl edit: round the live object through $EDITOR as YAML and
        update with whatever comes back (pkg/kubectl/cmd/edit.go's
        edit-reapply loop collapsed to one pass; KTCTL_EDITOR/EDITOR)."""
        import os
        import subprocess
        import tempfile
        pos, flags = self._flags(args)
        kind = self._resolve_kind(pos[0])
        ns = "" if self._cluster_scoped(kind) \
            else flags.get("namespace", "default")
        cur = self.api.get(kind, ns, pos[1])
        editor = os.environ.get("KTCTL_EDITOR") or os.environ.get(
            "EDITOR")
        if not editor:
            raise SystemExit("error: no KTCTL_EDITOR or EDITOR defined")
        manifest = self._canon_manifest(kind, cur)
        with tempfile.NamedTemporaryFile(
                "w", suffix=".yaml", delete=False) as f:
            yaml.safe_dump(manifest, f)
            path = f.name
        try:
            try:
                subprocess.run(editor.split() + [path], check=True)
            except subprocess.CalledProcessError:
                # vim :cq and friends — the conventional abort signal
                self._print("Edit cancelled, no changes made.")
                return
            with open(path) as f:
                edited = yaml.safe_load(f)
        finally:
            os.unlink(path)
        if edited is None or edited == manifest:
            self._print("Edit cancelled, no changes made.")
            return
        new_obj = self._decode_canon(kind, edited, cur)
        self.api.update(kind, new_obj)
        self._print(f"{self._plural(kind)}/{pos[1]} edited")

    def cmd_delete(self, args):
        pos, flags = self._flags(args)
        kind = self._resolve_kind(pos[0])
        ns = flags.get("namespace", "default")
        for obj in self._objs(kind, ns, pos[1] if len(pos) > 1 else "",
                              flags.get("selector", "")):
            self.api.delete(kind, getattr(obj, "namespace", ""), obj.name)
            self._print(f"{self._plural(kind)}/{obj.name} deleted")

    def cmd_scale(self, args):
        pos, flags = self._flags(args)
        kind = self._resolve_kind(pos[0])
        reps = int(flags["replicas"])
        self.api.scale(kind, flags.get("namespace", "default"), pos[1],
                       replicas=reps)
        self._print(f"{self._plural(kind)}/{pos[1]} scaled")

    def _mutate_meta(self, args, field: str):
        pos, flags = self._flags(args)
        kind = self._resolve_kind(pos[0])
        ns = flags.get("namespace", "default")
        obj = self._objs(kind, ns, pos[1])[0]
        d = getattr(obj, field)
        for kv in pos[2:]:
            if kv.endswith("-"):
                d.pop(kv[:-1], None)
            elif "=" in kv:
                k, _, v = kv.partition("=")
                d[k] = v
        self.api.update(kind, obj)
        self._print(f"{self._plural(kind)}/{pos[1]} {field[:-1]}ed")

    def cmd_label(self, args):
        self._mutate_meta(args, "labels")

    def cmd_annotate(self, args):
        self._mutate_meta(args, "annotations")

    def cmd_taint(self, args):
        pos, flags = self._flags(args)
        if pos[0] not in ("nodes", "node", "no"):
            raise SystemExit("error: taint only supports nodes")
        node = self.api.get("Node", "", pos[1])
        for spec in pos[2:]:
            if spec.endswith("-"):
                body = spec[:-1]
                key = body.split("=", 1)[0].split(":", 1)[0]
                node.taints = [t for t in node.taints if t.key != key]
                continue
            kv, _, effect = spec.rpartition(":")
            k, _, v = kv.partition("=")
            node.taints = list(node.taints) + [
                Taint(k, v, TaintEffect(effect))]
        self.api.update("Node", node)
        self._print(f"node/{pos[1]} tainted")

    def cmd_cordon(self, args):
        pos, _ = self._flags(args)
        node = self.api.get("Node", "", pos[0])
        node.unschedulable = True
        self.api.update("Node", node)
        self._print(f"node/{pos[0]} cordoned")

    def cmd_uncordon(self, args):
        pos, _ = self._flags(args)
        node = self.api.get("Node", "", pos[0])
        node.unschedulable = False
        self.api.update("Node", node)
        self._print(f"node/{pos[0]} uncordoned")

    def cmd_drain(self, args):
        """cordon + evict every pod on the node (kubectl drain,
        pkg/kubectl/cmd/drain.go; evictions honor PDBs server-side)."""
        pos, flags = self._flags(args)
        self.cmd_cordon([pos[0]])
        pods, _ = self.api.list("Pod")
        for p in pods:
            if p.node_name == pos[0]:
                self.api.evict(Eviction(p.name, p.namespace))
                self._print(f"pod/{p.name} evicted")

    def cmd_rollout(self, args):
        pos, flags = self._flags(args)
        if len(pos) < 3:
            raise SystemExit(
                "error: usage: rollout "
                "{status|history|undo|pause|resume|restart} KIND NAME")
        sub, kind_arg, name = pos[0], pos[1], pos[2]
        kind = self._resolve_kind(kind_arg)
        ns = flags.get("namespace", "default")
        obj = self.api.get(kind, ns, name)
        if sub == "status":
            ready = getattr(obj, "ready_replicas", 0)
            want = getattr(obj, "replicas", 0)
            if ready >= want:
                self._print(f'{self._plural(kind)} "{name}" successfully '
                            "rolled out")
            else:
                self._print(f"Waiting for rollout to finish: {ready} of "
                            f"{want} updated replicas are available...")
        elif sub == "history":
            for rev in getattr(obj, "revision_history", []) or ["<none>"]:
                self._print(str(rev))
        elif sub == "undo":
            hist = getattr(obj, "revision_history", None)
            if not hist:
                raise SystemExit("error: no rollout history found")
            obj.template = hist[-1]
            self.api.update(kind, obj)
            self._print(f"{self._plural(kind)}/{name} rolled back")
        elif sub in ("pause", "resume"):
            # kubectl rollout pause/resume (cmd/rollout_pause.go): the
            # deployment controller skips paused deployments, freezing
            # the rollout without touching the spec
            if not hasattr(obj, "paused"):
                raise SystemExit(
                    f"error: {kind} does not support pausing")
            want = sub == "pause"
            if obj.paused == want:
                # kubectl's exact wording (cmd/rollout_pause.go /
                # rollout_resume.go)
                raise SystemExit(
                    f"error: {self._plural(kind)}/{name} is "
                    f"{'already paused' if want else 'not paused'}")
            obj.paused = want
            self.api.update(kind, obj)
            self._print(f"{self._plural(kind)}/{name} {sub}d")
        elif sub == "restart":
            # kubectl rollout restart (cmd/rollout_restart.go): stamp a
            # restartedAt annotation on the POD TEMPLATE — the template
            # change hashes differently, so the controller rolls new pods
            # without any spec change
            tmpl = getattr(obj, "template", None)
            if tmpl is None or not hasattr(tmpl, "annotations"):
                raise SystemExit(
                    f"error: {kind} does not support restart")
            import time as _time
            tmpl.annotations["kubectl.kubernetes.io/restartedAt"] = \
                str(_time.time())
            self.api.update(kind, obj)
            self._print(f"{self._plural(kind)}/{name} restarted")
        else:
            raise SystemExit(f"error: unknown rollout subcommand {sub!r}")

    def cmd_top(self, args):
        pos, flags = self._flags(args)
        if pos and pos[0] in ("node", "nodes", "no"):
            pods, _ = self.api.list("Pod")
            nodes, _ = self.api.list("Node")
            usage = {}
            for p in pods:
                if p.node_name:
                    r = p.resource_request()
                    u = usage.setdefault(p.node_name, [0, 0])
                    u[0] += r.milli_cpu
                    u[1] += r.memory
            self._print("NAME  CPU(cores)  MEMORY(bytes)")
            for n in nodes:
                u = usage.get(n.name, [0, 0])
                self._print(f"{n.name}  {u[0]}m  {u[1]}")
            return
        if pos and pos[0] in ("pod", "pods", "po"):
            # kubectl top pod (metrics-server path): per-pod usage — the
            # hollow runtime's actual-usage annotations when scripted
            # (the cadvisor stand-in), requests otherwise
            from kubernetes_tpu.nodes.kubelet import ACTUAL_MEM_ANNOTATION
            ns = flags.get("namespace", "default")
            pods, _ = self.api.list("Pod")
            self._print("NAME  CPU(cores)  MEMORY(bytes)")
            for p in pods:
                if p.namespace != ns and "all-namespaces" not in flags:
                    continue
                if not p.node_name:
                    continue  # metrics exist only for running pods
                r = p.resource_request()
                mem = int(p.annotations.get(ACTUAL_MEM_ANNOTATION,
                                            r.memory))
                self._print(f"{p.name}  {r.milli_cpu}m  {mem}")
            return
        raise SystemExit("error: usage: top {node|pod} [...]")

    def cmd_api_resources(self, args):
        self._print("NAME  APIGROUP  KIND  NAMESPACED")
        rows = self._discovery_resources() or [
            {"name": res, "group": "", "kind": kind,
             "namespaced": not cluster}
            for kind, (res, cluster) in KIND_INFO.items()]
        for r in sorted(rows, key=lambda r: (r.get("group", ""), r["name"])):
            self._print(f"{r['name']}  {r.get('group', '')}  {r['kind']}  "
                        f"{str(r['namespaced']).lower()}")

    def cmd_auth(self, args):
        """kubectl auth can-i VERB RESOURCE [NAME] [--as user] [--as-group g]
        [-n ns] — evaluates the configured authorizer chain
        (pkg/kubectl/cmd/auth/cani.go via SelfSubjectAccessReview)."""
        pos, flags = self._flags(args)
        if pos[:1] != ["can-i"] or len(pos) < 3:
            raise SystemExit("error: usage: auth can-i VERB RESOURCE [NAME]")
        authorizer = getattr(self.api, "authorizer", None)
        if authorizer is None:
            raise SystemExit("error: server has no authorizer configured")
        from kubernetes_tpu.auth.authz import ALLOW, Attributes
        from kubernetes_tpu.api.rbac import UserInfo
        groups = [g for g in flags.get("as-group", "").split(",") if g]
        user = UserInfo(name=flags.get("as", "system:admin"), groups=groups)
        attrs = Attributes(
            user=user, verb=pos[1], resource=pos[2],
            namespace=flags.get("namespace", "default"),
            name=pos[3] if len(pos) > 3 else "")
        self._print("yes" if authorizer.authorize(attrs) == ALLOW else "no")

    def cmd_explain(self, args):
        """kubectl explain KIND[.field[.field]]: field documentation from
        the live OpenAPI document (kubectl cmd/explain.go reads the same
        swagger the server publishes — here server/openapi.py, which
        derives from the serving dataclasses, so explain can never drift
        from what the server accepts)."""
        pos, _flags = self._flags(args)
        if not pos:
            raise SystemExit("error: resource name required")
        path = pos[0].split(".")
        kind = self._resolve_kind(path[0])
        store = getattr(self.api, "store", None)
        if store is not None:
            from kubernetes_tpu.server.openapi import build_spec
            spec = build_spec(store)
        else:
            # remote backend: fetch the server-PUBLISHED document so CRD
            # definitions the server serves are visible here too
            spec = self.api.openapi()
        schema = spec["definitions"].get(kind)
        if schema is None:
            raise SystemExit(
                f"error: couldn't find resource for {path[0]!r}")
        is_array = False
        for field_name in path[1:]:
            props = schema.get("properties", {})
            if field_name not in props:
                raise SystemExit(
                    f'error: field "{field_name}" does not exist')
            schema = props[field_name]
            is_array = schema.get("type") == "array"
            if is_array:
                schema = schema.get("items", {})
        self._print(f"KIND:     {kind}")
        self._print(f"VERSION:  v1\n")
        if len(path) > 1:
            t = schema.get("type", "object")
            self._print(f"FIELD:    {path[-1]} "
                        f"<{'[]' + t if is_array else t}>")
        props = schema.get("properties")
        if props:
            self._print("FIELDS:")
            for fname, fschema in sorted(props.items()):
                self._print(f"   {fname}\t<{fschema.get('type', 'object')}>")

    def cmd_run(self, args):
        """kubectl run NAME --image=IMG [--replicas=N] (cmd/run.go, the
        1.7 generator behavior): one pod by default, a Deployment when
        --replicas is given."""
        pos, flags = self._flags(args)
        if not pos or not flags.get("image"):
            raise SystemExit("error: usage: run NAME --image=IMAGE")
        ns = flags.get("namespace", "default")
        name = pos[0]
        from kubernetes_tpu.api.types import (
            Container,
            LabelSelector,
            Pod,
        )
        reps = flags.get("replicas")
        if reps is None:
            pod = Pod(name=name, namespace=ns, labels={"run": name},
                      containers=[Container(name=name,
                                            image=flags["image"])])
            self.api.create("Pod", pod)
            self._print(f"pod/{name} created")
            return
        from kubernetes_tpu.api.workloads import Deployment
        dep = Deployment(
            name=name, namespace=ns, replicas=int(reps),
            selector=LabelSelector(match_labels={"run": name}),
            template=Pod(name="", namespace=ns, labels={"run": name},
                         containers=[Container(name=name,
                                               image=flags["image"])]))
        self.api.create("Deployment", dep)
        self._print(f"deployment/{name} created")

    def cmd_autoscale(self, args):
        """kubectl autoscale KIND NAME --min=N --max=M [--cpu-percent=P]
        (cmd/autoscale.go): create an HPA targeting the workload."""
        pos, flags = self._flags(args)
        if len(pos) < 2 or "max" not in flags:
            raise SystemExit(
                "error: usage: autoscale KIND NAME --max=N [--min=N] "
                "[--cpu-percent=P]")
        kind = self._resolve_kind(pos[0])
        ns = flags.get("namespace", "default")
        from kubernetes_tpu.server.apiserver_lite import NotFound
        try:
            self.api.get(kind, ns, pos[1])  # target must exist
        except NotFound as e:
            raise SystemExit(f"error: {e}") from None
        from kubernetes_tpu.api.workloads import HorizontalPodAutoscaler
        lo, hi = int(flags.get("min", 1)), int(flags["max"])
        if hi <= 0 or lo > hi:
            # kubectl rejects this at the CLI; letting it through would
            # pin the workload at min forever (the controller clamps
            # max(min, min(max, desired)))
            raise SystemExit(
                f"error: --max={hi} must be at least 1 and >= --min={lo}")
        hpa = HorizontalPodAutoscaler(
            name=pos[1], namespace=ns, target_kind=kind,
            target_name=pos[1], min_replicas=lo, max_replicas=hi,
            target_cpu_utilization=int(flags.get("cpu-percent", 80)))
        self.api.create("HorizontalPodAutoscaler", hpa)
        self._print(f"horizontalpodautoscaler/{pos[1]} autoscaled")

    def cmd_expose(self, args):
        """kubectl expose KIND NAME --port P [--target-port T] [--name N]:
        create a Service selecting the workload's pods
        (pkg/kubectl/cmd/expose.go + the service generator)."""
        from kubernetes_tpu.api.workloads import (
            Service,
            ServicePort,
            selector_of,
        )
        pos, flags = self._flags(args)
        if len(pos) < 2 or "port" not in flags:
            raise SystemExit("error: usage: expose KIND NAME --port P")
        kind = self._resolve_kind(pos[0])
        ns = flags.get("namespace", "default")
        obj = self.api.get(kind, ns, pos[1])
        sel = selector_of(obj)
        if sel.match_expressions:
            raise SystemExit("error: cannot expose via expression selector "
                             "(service selectors are equality-only)")
        if not sel.match_labels:
            raise SystemExit(f"error: {kind} {pos[1]} has no selector")
        try:
            port = int(flags["port"])
            target = int(flags.get("target-port", port))
        except ValueError:
            raise SystemExit("error: --port/--target-port must be integers")
        svc = Service(
            flags.get("name", pos[1]), ns, selector=dict(sel.match_labels),
            ports=[ServicePort(port=port, target_port=target)])
        self.api.create("Service", svc)
        self._print(f"service/{svc.name} exposed")

    def cmd_set(self, args):
        """kubectl set image KIND NAME CONTAINER=IMAGE...: update pod
        template images (pkg/kubectl/cmd/set/set_image.go) — rollouts pick
        the change up through the normal template-hash machinery."""
        import dataclasses as _dc
        pos, flags = self._flags(args)
        if pos[:1] != ["image"] or len(pos) < 4:
            raise SystemExit(
                "error: usage: set image KIND NAME CONTAINER=IMAGE")
        kind = self._resolve_kind(pos[1])
        ns = flags.get("namespace", "default")
        obj = self.api.get(kind, ns, pos[2])
        template = getattr(obj, "template", None)
        if template is None:
            raise SystemExit(f"error: {kind} has no pod template")
        if any("=" not in kv for kv in pos[3:]):
            raise SystemExit(
                "error: usage: set image KIND NAME CONTAINER=IMAGE")
        updates = dict(kv.split("=", 1) for kv in pos[3:])
        new_containers = []
        changed = False
        for c in template.containers:
            if c.name in updates or "*" in updates:
                img = updates.get(c.name, updates.get("*"))
                new_containers.append(_dc.replace(c, image=img))
                changed = True
            else:
                new_containers.append(c)
        if not changed:
            raise SystemExit("error: no matching container")
        new_template = _dc.replace(template, containers=new_containers)
        self.api.update(kind, _dc.replace(obj, template=new_template),
                        expect_rv=obj.resource_version)
        self._print(f"{kind.lower()}/{pos[2]} image updated")

    def cmd_federate(self, args):
        """kubefed verbs (federation/cmd kubefed + federated-RS CRUD):
        federate join <cluster> | unjoin <cluster> | clusters |
        federate create rs <name> --replicas N [--preferences JSON]
                 [--cpu m] [--selector k=v] | scale rs <name> --replicas N |
        federate get | sync"""
        if self.federation is None:
            raise SystemExit("error: no federation control plane configured")
        from kubernetes_tpu.api.types import LabelSelector, make_pod
        from kubernetes_tpu.api.workloads import ReplicaSet
        from kubernetes_tpu.federation.controller import (
            FEDERATED_RS_KIND,
            FederatedReplicaSet,
            FederatedReplicaSetController,
        )
        from kubernetes_tpu.federation.planner import PREFERENCES_ANNOTATION

        pos, flags = self._flags(list(args))
        if not pos:
            raise SystemExit("error: federate verb required")
        verb = pos[0]
        plane = self.federation

        def workload_args():
            """Shared name/namespace/selector/pod-template parsing for the
            three `federate create` flavors."""
            name = pos[2]
            ns = flags.get("namespace", "default")
            sel = dict(kv.split("=", 1) for kv in
                       flags.get("selector", f"app={name}").split(","))
            tmpl_pod = make_pod("", namespace=ns, labels=dict(sel),
                                cpu=int(flags.get("cpu", 100)))
            return name, ns, sel, tmpl_pod
        if verb == "join":
            name = pos[1]
            if name not in self.federation_contexts:
                raise SystemExit(f"error: unknown cluster context {name!r}")
            plane.join(name, self.federation_contexts[name])
            self._print(f"cluster/{name} joined")
        elif verb == "unjoin":
            plane.unjoin(pos[1])
            self._print(f"cluster/{pos[1]} unjoined")
        elif verb == "clusters":
            for c in plane.api.list("Cluster")[0]:
                state = "Ready" if c.ready and c.name in plane.members \
                    else "NotReady"
                self._print(f"{c.name}\t{state}")
        elif verb == "create" and pos[1:2] == ["rs"]:
            name, ns, sel, tmpl_pod = workload_args()
            frs = FederatedReplicaSet(
                name=name, namespace=ns,
                replicas=int(flags.get("replicas", 1)),
                template=ReplicaSet(
                    name=name, namespace=ns,
                    selector=LabelSelector(match_labels=dict(sel)),
                    template=tmpl_pod))
            if flags.get("preferences"):
                frs.annotations[PREFERENCES_ANNOTATION] = flags["preferences"]
            plane.api.create(FEDERATED_RS_KIND, frs)
            self._print(f"federatedreplicaset/{name} created")
        elif verb == "scale" and pos[1:2] == ["rs"]:
            ns = flags.get("namespace", "default")
            cur = plane.api.get(FEDERATED_RS_KIND, ns, pos[2])
            import dataclasses as _dc
            plane.api.update(FEDERATED_RS_KIND, _dc.replace(
                cur, replicas=int(flags["replicas"])),
                expect_rv=cur.resource_version)
            self._print(f"federatedreplicaset/{pos[2]} scaled")
        elif verb == "get":
            from kubernetes_tpu.federation.controller import (
                FEDERATED_DEPLOY_KIND,
            )
            from kubernetes_tpu.federation.service_dns import (
                FEDERATED_SERVICE_KIND,
            )
            for fkind in (FEDERATED_RS_KIND, FEDERATED_DEPLOY_KIND):
                for frs in plane.api.list(fkind)[0]:
                    self._print(f"{fkind.lower()}/{frs.namespace}/"
                                f"{frs.name}\treplicas={frs.replicas}\t"
                                f"ready={frs.ready_replicas}")
            for fsvc in plane.api.list(FEDERATED_SERVICE_KIND)[0]:
                self._print(
                    f"federatedservice/{fsvc.namespace}/{fsvc.name}\t"
                    f"serving={','.join(fsvc.serving_clusters) or '<none>'}")
        elif verb == "create" and pos[1:2] == ["deploy"]:
            from kubernetes_tpu.api.workloads import Deployment
            from kubernetes_tpu.federation.controller import (
                FEDERATED_DEPLOY_KIND,
                FederatedDeployment,
            )
            name, ns, sel, tmpl_pod = workload_args()
            fd = FederatedDeployment(
                name=name, namespace=ns,
                replicas=int(flags.get("replicas", 1)),
                template=Deployment(
                    name=name, namespace=ns,
                    selector=LabelSelector(match_labels=dict(sel)),
                    template=tmpl_pod))
            plane.api.create(FEDERATED_DEPLOY_KIND, fd)
            self._print(f"federateddeployment/{name} created")
        elif verb == "create" and pos[1:2] == ["service"]:
            from kubernetes_tpu.api.workloads import Service, ServicePort
            from kubernetes_tpu.federation.service_dns import (
                FEDERATED_SERVICE_KIND,
                FederatedService,
            )
            name, ns, sel, _tmpl = workload_args()
            plane.api.create(FEDERATED_SERVICE_KIND, FederatedService(
                name=name, namespace=ns,
                template=Service(name=name, namespace=ns, selector=sel,
                                 ports=[ServicePort(
                                     port=int(flags.get("port", 80)))])))
            self._print(f"federatedservice/{name} created")
        elif verb == "dns":
            # read path for the provider zone: `federate dns [name-substr]`
            from kubernetes_tpu.federation.service_dns import (
                FederatedServiceController,
            )
            sub = pos[1] if len(pos) > 1 else ""
            dns = FederatedServiceController(plane).dns
            for (rname, rtype), rec in sorted(dns.records.items()):
                if sub and sub not in rname:
                    continue
                self._print(f"{rname}\t{rtype}\t{','.join(rec.values)}")
        elif verb == "sync":
            from kubernetes_tpu.federation.controller import (
                FederatedDeploymentController,
            )
            from kubernetes_tpu.federation.service_dns import (
                FederatedServiceController,
            )
            FederatedReplicaSetController(plane).sync_all()
            FederatedDeploymentController(plane).sync_all()
            FederatedServiceController(plane).sync_all()
            self._print("synced")
        else:
            raise SystemExit(f"error: unknown federate verb {verb!r}")

    def _kubelet_for(self, node_name: str):
        kubelets = getattr(self, "kubelets", None) or {}
        target = kubelets.get(node_name)
        if target is None:
            raise SystemExit(
                f"error: no kubelet endpoint registered for node "
                f"{node_name!r}")
        return target

    def cmd_logs(self, args):
        """kubectl logs: resolve the pod's node, then read
        /containerLogs/<ns>/<pod> from that node's kubelet API — the
        apiserver-proxies-to-kubelet path (pkg/kubelet/server/server.go
        InstallDebuggingHandlers; kubectl cmd/logs.go)."""
        import urllib.request

        from kubernetes_tpu.nodes.kubelet_server import KubeletApiError
        from kubernetes_tpu.server.apiserver_lite import NotFound

        pos, flags = self._flags(args)
        if not pos:
            raise SystemExit("error: pod name required")
        ns = flags.get("namespace", "default")
        try:
            pod = self.api.get("Pod", ns, pos[0])
        except NotFound as e:
            raise SystemExit(f"error: {e}") from None
        if not pod.node_name:
            raise SystemExit(f"error: pod {pos[0]!r} is not scheduled yet")
        target = self._kubelet_for(pod.node_name)
        tail = flags.get("tail")
        if isinstance(target, str):  # kubelet API base URL
            q = f"?tailLines={tail}" if tail is not None else ""
            try:
                with urllib.request.urlopen(
                        f"{target}/containerLogs/{ns}/{pos[0]}{q}") as r:
                    self._print(r.read().decode().rstrip("\n"))
            except urllib.error.HTTPError as e:
                raise SystemExit(
                    f"error: logs failed: {e.read().decode() or e}"
                ) from None
            return
        # in-process HollowKubelet: the SAME serve_logs the HTTP server
        # routes through — one implementation of the log semantics
        try:
            self._print(target.serve_logs(ns, pos[0], tail=tail))
        except KubeletApiError as e:
            raise SystemExit(f"error: {e}") from None

    def cmd_exec(self, args):
        """kubectl exec (non-streaming form): POST the command to the
        node's kubelet /exec endpoint."""
        import urllib.request
        from urllib.parse import quote

        # everything after "--" is the command verbatim (kubectl exec's
        # arg contract) — it must never reach the flag parser
        args = list(args)
        if "--" in args:
            split = args.index("--")
            args, cmd_args = args[:split], args[split + 1:]
        else:
            cmd_args = []
        pos, flags = self._flags(args)
        if not pos or not cmd_args:
            raise SystemExit("error: usage: exec POD -- COMMAND")
        ns = flags.get("namespace", "default")
        from kubernetes_tpu.nodes.kubelet_server import KubeletApiError
        from kubernetes_tpu.server.apiserver_lite import NotFound

        name, cmd = pos[0], " ".join(cmd_args)
        try:
            pod = self.api.get("Pod", ns, name)
        except NotFound as e:
            raise SystemExit(f"error: {e}") from None
        if not pod.node_name:
            raise SystemExit(f"error: pod {name!r} is not scheduled yet")
        target = self._kubelet_for(pod.node_name)
        if isinstance(target, str):
            req = urllib.request.Request(
                f"{target}/exec/{ns}/{name}?command={quote(cmd)}",
                data=b"", method="POST")
            try:
                with urllib.request.urlopen(req) as r:
                    self._print(r.read().decode().rstrip("\n"))
            except Exception as e:
                raise SystemExit(f"error: exec failed: {e}") from None
            return
        try:
            self._print(target.serve_exec(ns, name, cmd))
        except KubeletApiError as e:
            raise SystemExit(f"error: {e}") from None

    def cmd_attach(self, args):
        """kubectl attach (non-streaming form): attach to the RUNNING
        container's output via the node's kubelet /attach endpoint
        (kubectl cmd/attach.go; SPDY streaming elided like exec)."""
        import urllib.request

        from kubernetes_tpu.nodes.kubelet_server import KubeletApiError
        from kubernetes_tpu.server.apiserver_lite import NotFound

        pos, flags = self._flags(args)
        if not pos:
            raise SystemExit("error: pod name required")
        ns = flags.get("namespace", "default")
        try:
            pod = self.api.get("Pod", ns, pos[0])
        except NotFound as e:
            raise SystemExit(f"error: {e}") from None
        if not pod.node_name:
            raise SystemExit(f"error: pod {pos[0]!r} is not scheduled yet")
        target = self._kubelet_for(pod.node_name)
        if isinstance(target, str):
            req = urllib.request.Request(f"{target}/attach/{ns}/{pos[0]}",
                                         data=b"", method="POST")
            try:
                with urllib.request.urlopen(req) as r:
                    self._print(r.read().decode().rstrip("\n"))
            except Exception as e:
                raise SystemExit(f"error: attach failed: {e}") from None
            return
        try:
            self._print(target.serve_attach(ns, pos[0]))
        except KubeletApiError as e:
            raise SystemExit(f"error: {e}") from None

    def cmd_port_forward(self, args):
        """kubectl port-forward: bind a REAL local TCP port; every
        connection is answered with one round of the pod's port stream
        fetched through the kubelet (cmd/portforward.go; the kubelet leg
        is /portForward). Runs on a daemon thread (the in-process harness
        cannot block the CLI loop the way kubectl's foreground does);
        forwarders are exposed on self.port_forwards with .local_port and
        .stop()."""
        import socket
        import threading

        from kubernetes_tpu.nodes.kubelet_server import KubeletApiError
        from kubernetes_tpu.server.apiserver_lite import NotFound

        pos, flags = self._flags(args)
        if len(pos) < 2 or ":" not in pos[1]:
            raise SystemExit(
                "error: usage: port-forward POD LOCAL:REMOTE")
        ns = flags.get("namespace", "default")
        local_s, _, remote_s = pos[1].partition(":")
        try:
            local, remote = int(local_s), int(remote_s)
        except ValueError:
            raise SystemExit(
                f"error: invalid port mapping {pos[1]!r}") from None
        try:
            pod = self.api.get("Pod", ns, pos[0])
        except NotFound as e:
            raise SystemExit(f"error: {e}") from None
        if not pod.node_name:
            raise SystemExit(f"error: pod {pos[0]!r} is not scheduled yet")
        target = self._kubelet_for(pod.node_name)

        def fetch() -> bytes:
            if isinstance(target, str):
                import urllib.request
                with urllib.request.urlopen(
                        f"{target}/portForward/{ns}/{pos[0]}"
                        f"?port={remote}") as r:
                    return r.read()
            return target.serve_port(ns, pos[0], remote)

        try:
            fetch()  # fail fast: bad pod/port surfaces NOW, not per-conn
        except KubeletApiError as e:
            raise SystemExit(f"error: {e}") from None
        except Exception as e:
            raise SystemExit(f"error: port-forward failed: {e}") from None

        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            srv.bind(("127.0.0.1", local))
        except OSError as e:
            raise SystemExit(
                f"error: unable to listen on port {local}: {e}") from None
        srv.listen(8)

        class Forwarder:
            local_port = srv.getsockname()[1]

            def __init__(self):
                self._alive = True
                self._thread = threading.Thread(target=self._loop,
                                                daemon=True)
                self._thread.start()

            def _loop(self):
                while self._alive:
                    try:
                        conn, _addr = srv.accept()
                    except OSError:
                        return
                    try:
                        conn.sendall(fetch())
                    except Exception:
                        pass
                    finally:
                        conn.close()

            def stop(self):
                self._alive = False
                try:
                    srv.close()
                except OSError:
                    pass

        fwd = Forwarder()
        if not hasattr(self, "port_forwards"):
            self.port_forwards = []
        self.port_forwards.append(fwd)
        self._print(f"Forwarding from 127.0.0.1:{fwd.local_port} -> "
                    f"{remote}")

    def cmd_version(self, args):
        from kubernetes_tpu.server.rest_http import VERSION
        self._print(f"Client Version: {VERSION['gitVersion']}")
        # ask the CONNECTED backend when it can answer (kubectl prints
        # both precisely to diagnose client/server skew)
        server_v = VERSION["gitVersion"]
        version_fn = getattr(self.api, "version", None)
        if callable(version_fn):
            try:
                server_v = version_fn().get("gitVersion", server_v)
            except Exception as e:
                raise SystemExit(
                    f"error: could not fetch server version: {e}"
                ) from None
        self._print(f"Server Version: {server_v}")


def main(argv: Optional[Sequence[str]] = None) -> int:
    """Entry point for `python -m kubernetes_tpu.cli.ktctl --server URL ...`
    (remote mode) — in-process mode is the library API (Ktctl(api))."""
    argv = list(sys.argv[1:] if argv is None else argv)
    server = None
    if argv[:1] and argv[0].startswith("--server"):
        if "=" in argv[0]:
            server = argv.pop(0).split("=", 1)[1]
        else:
            argv.pop(0)
            server = argv.pop(0)
    if server:
        from kubernetes_tpu.cli.rest_client import RestClient

        api = RestClient(server)
    else:
        raise SystemExit("error: --server URL required outside a test "
                         "harness (in-process mode is the library API)")
    return Ktctl(api).run(argv)


if __name__ == "__main__":
    raise SystemExit(main())
