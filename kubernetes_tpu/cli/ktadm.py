"""ktadm: the kubeadm-equivalent cluster bootstrap tool.

Mirrors cmd/kubeadm/app's phase architecture (cmd/kubeadm/app/phases/):

  ktadm init            preflight -> certs -> kubeconfig -> control-plane
                        (static manifests) -> bootstrap-token -> RBAC
  ktadm join            bootstrap-token auth -> CSR -> auto-approve/sign
                        -> node registration with the signed identity
                        (app/discovery + app/node: the TLS bootstrap flow)
  ktadm token           create | list | delete
  ktadm preflight       run the checks alone

Differences from the reference are deliberate and TPU-framework-shaped:
"certs" are the HMAC identity records CertAuthenticator verifies (the
x509 stand-in used across this framework), the control-plane manifests
are static-pod JSON the hollow kubelet's file source loads
(nodes/kubelet.py load_static_dir, mirroring kubeadm writing
/etc/kubernetes/manifests for the real kubelet), and init wires an
in-process ApiServer instead of systemd units.
"""

from __future__ import annotations

import hashlib
import json
import os
import secrets as pysecrets
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu.api.cluster import CertificateSigningRequest, Secret
from kubernetes_tpu.api.rbac import UserInfo
from kubernetes_tpu.api.types import make_node
from kubernetes_tpu.api.workloads import Namespace
from kubernetes_tpu.auth.authn import (
    BootstrapTokenAuthenticator,
    CertAuthenticator,
    Credential,
    ServiceAccountTokenAuthenticator,
    TokenAuthenticator,
    UnionAuthenticator,
)
from kubernetes_tpu.server.apiserver import ApiServer
from kubernetes_tpu.server.apiserver_lite import Conflict, NotFound

CONTROL_PLANE_COMPONENTS = ("kube-apiserver", "kube-controller-manager",
                            "kube-scheduler")


def generate_token() -> str:
    """kubeadm token format: <6 lowercase alnum>.<16 lowercase alnum>
    (cmd/kubeadm/app/util/token/tokens.go)."""
    alphabet = "abcdefghijklmnopqrstuvwxyz0123456789"
    tid = "".join(pysecrets.choice(alphabet) for _ in range(6))
    sec = "".join(pysecrets.choice(alphabet) for _ in range(16))
    return f"{tid}.{sec}"


def ca_hash(ca_key: bytes) -> str:
    """The --discovery-token-ca-cert-hash pin (app/discovery/token):
    joiners verify they reached the intended cluster."""
    return "sha256:" + hashlib.sha256(ca_key).hexdigest()


@dataclass
class InitResult:
    api: ApiServer
    ca: CertAuthenticator
    ca_key: bytes
    bootstrap: BootstrapTokenAuthenticator
    token: str
    admin_cred: Credential
    workdir: str
    kubeconfigs: Dict[str, dict] = field(default_factory=dict)

    def join_command(self) -> str:
        return (f"ktadm join --token {self.token} "
                f"--discovery-token-ca-cert-hash {ca_hash(self.ca_key)}")


class KtAdm:
    """Phase runner. Each phase_* is independently invocable (the kubeadm
    `alpha phase` palette); `init` composes them in reference order."""

    def __init__(self, out=None, now=time.time):
        self.out = out if out is not None else sys.stdout
        self._now = now

    def _print(self, s: str) -> None:
        self.out.write(s + "\n")

    # ------------------------------------------------------------ preflight

    def preflight(self, workdir: str) -> List[str]:
        """app/preflight/checks.go, the in-process subset: workdir state,
        clock sanity, prior-init detection. Returns failed checks."""
        errors: List[str] = []
        parent = os.path.dirname(os.path.abspath(workdir)) or "."
        if not os.path.isdir(parent):
            errors.append(f"workdir parent {parent!r} does not exist")
        elif not os.access(parent, os.W_OK):
            errors.append(f"workdir parent {parent!r} is not writable")
        if os.path.exists(os.path.join(workdir, "pki", "ca.key")):
            errors.append(
                f"{workdir}/pki/ca.key already exists — cluster already "
                f"initialized (run `ktadm reset` first)")
        manifests = os.path.join(workdir, "manifests")
        if os.path.isdir(manifests) and os.listdir(manifests):
            errors.append(f"{manifests} is not empty")
        if self._now() < 1_000_000_000:  # clock sanity (NTP check analog)
            errors.append("system clock is before 2001 — fix time sync")
        for e in errors:
            self._print(f"[preflight] FAIL: {e}")
        if not errors:
            self._print("[preflight] all checks passed")
        return errors

    # ---------------------------------------------------------------- certs

    def phase_certs(self, workdir: str) -> Tuple[CertAuthenticator, bytes]:
        """app/phases/certs: mint the CA and the component identities
        signed by it."""
        pki = os.path.join(workdir, "pki")
        os.makedirs(pki, exist_ok=True)
        ca_key = pysecrets.token_bytes(32)
        with open(os.path.join(pki, "ca.key"), "wb") as f:
            f.write(ca_key)
        ca = CertAuthenticator(ca_key)
        identities = {
            "admin": ("kubernetes-admin", ["system:masters"]),
            "controller-manager": ("system:kube-controller-manager", []),
            "scheduler": ("system:kube-scheduler", []),
            "apiserver": ("kube-apiserver", []),
        }
        for fname, (cn, orgs) in identities.items():
            cert = ca.sign(cn, orgs)
            with open(os.path.join(pki, fname + ".cert.json"), "w") as f:
                json.dump(cert, f)
        self._print(f"[certs] CA + {len(identities)} component "
                    f"identities written to {pki}")
        return ca, ca_key

    # ----------------------------------------------------------- kubeconfig

    def phase_kubeconfig(self, workdir: str, server: str) -> Dict[str, dict]:
        """app/phases/kubeconfig: one context file per component."""
        pki = os.path.join(workdir, "pki")
        out: Dict[str, dict] = {}
        for comp in ("admin", "controller-manager", "scheduler"):
            with open(os.path.join(pki, comp + ".cert.json")) as f:
                cert = json.load(f)
            cfg = {"server": server, "user": cert["cn"], "cert": cert}
            path = os.path.join(workdir, comp + ".conf")
            with open(path, "w") as f:
                json.dump(cfg, f)
            out[comp] = cfg
        self._print(f"[kubeconfig] wrote {len(out)} kubeconfig files")
        return out

    # -------------------------------------------------------- control plane

    def phase_control_plane(self, workdir: str) -> List[str]:
        """app/phases/controlplane: static-pod manifests the kubelet file
        source runs (nodes/kubelet.py load_static_dir reads this dir)."""
        manifests = os.path.join(workdir, "manifests")
        os.makedirs(manifests, exist_ok=True)
        written = []
        for comp in CONTROL_PLANE_COMPONENTS:
            manifest = {
                "apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": comp, "namespace": "kube-system",
                             "labels": {"component": comp,
                                        "tier": "control-plane"}},
                "spec": {"containers": [{
                    "name": comp,
                    "image": f"ktpu/{comp}:v1.7-tpu",
                    "resources": {"requests": {"cpu": "250m",
                                               "memory": "128Mi"}},
                }], "hostNetwork": True},
            }
            path = os.path.join(manifests, comp + ".json")
            with open(path, "w") as f:
                json.dump(manifest, f, indent=1)
            written.append(path)
        self._print(f"[control-plane] wrote {len(written)} static-pod "
                    f"manifests to {manifests}")
        return written

    # ------------------------------------------------------ bootstrap token

    def phase_bootstrap_token(self, api: ApiServer,
                              bootstrap: BootstrapTokenAuthenticator,
                              token: Optional[str] = None,
                              ttl: float = 86400.0) -> str:
        """app/phases/token: register the token with the authenticator and
        persist it as a kube-system Secret (bootstrap.kubernetes.io/token),
        which is what `ktadm token list` reads back."""
        token = token or generate_token()
        tid, _, sec = token.partition(".")
        bootstrap.add_token(tid, sec, ttl=ttl)
        api.store.create("Secret", Secret(
            f"bootstrap-token-{tid}", "kube-system",
            data={"token-id": tid, "token-secret": sec,
                  "expiration": str(self._now() + ttl),
                  "usage-bootstrap-authentication": "true"}))
        self._print(f"[bootstrap-token] created token {tid}.<redacted>")
        return token

    # ------------------------------------------------------- bootstrap RBAC

    def phase_bootstrap_rbac(self, api: ApiServer) -> None:
        """app/phases/bootstraptoken/node: let the system:bootstrappers
        group post CSRs (the kubeadm:kubelet-bootstrap binding to
        system:node-bootstrapper)."""
        from kubernetes_tpu.api.rbac import (
            ClusterRole,
            ClusterRoleBinding,
            PolicyRule,
            RoleRef,
            Subject,
        )
        api.store.create("ClusterRole", ClusterRole(
            "system:node-bootstrapper", rules=[
                PolicyRule(verbs=["create", "get", "list", "watch"],
                           resources=["certificatesigningrequests"])]))
        api.store.create("ClusterRoleBinding", ClusterRoleBinding(
            "kubeadm:kubelet-bootstrap",
            subjects=[Subject("Group", "system:bootstrappers")],
            role_ref=RoleRef("ClusterRole", "system:node-bootstrapper")))
        self._print("[bootstrap-rbac] kubelet-bootstrap binding installed")

    # ------------------------------------------------------------------ init

    def init(self, workdir: str, server: str = "in-process",
             token: Optional[str] = None) -> InitResult:
        errors = self.preflight(workdir)
        if errors:
            raise SystemExit("error: preflight checks failed")
        ca, ca_key = self.phase_certs(workdir)
        kubeconfigs = self.phase_kubeconfig(workdir, server)
        self.phase_control_plane(workdir)

        bootstrap = BootstrapTokenAuthenticator(now=self._now)
        authn = UnionAuthenticator([
            TokenAuthenticator({}),
            bootstrap,
            ServiceAccountTokenAuthenticator(ca_key),
            CertAuthenticator(ca_key),
        ])
        api = ApiServer(auth=True, authenticator=authn)
        for ns in ("default", "kube-system", "kube-public"):
            api.store.create("Namespace", Namespace(ns))
        api.bootstrap_rbac()
        self.phase_bootstrap_rbac(api)
        tok = self.phase_bootstrap_token(api, bootstrap, token=token)
        admin_cred = Credential(cert=kubeconfigs["admin"]["cert"])
        res = InitResult(api=api, ca=ca, ca_key=ca_key, bootstrap=bootstrap,
                         token=tok, admin_cred=admin_cred, workdir=workdir,
                         kubeconfigs=kubeconfigs)
        self._print("Your control plane has initialized successfully!")
        self._print("Join nodes with:\n  " + res.join_command())
        return res

    # ------------------------------------------------------------------ join

    def join(self, cluster: InitResult, node_name: str,
             token: str, ca_cert_hash: str = "",
             cpu: int = 4000, memory: int = 32 << 30) -> Credential:
        """The TLS-bootstrap join flow (app/discovery + kubelet
        bootstrap): authenticate with the bootstrap token, pin the CA,
        post a CSR, let csrapproving/csrsigning issue the node identity,
        then register the Node using it."""
        if ca_cert_hash and ca_cert_hash != ca_hash(cluster.ca_key):
            raise SystemExit(
                "error: cluster CA does not match "
                "--discovery-token-ca-cert-hash (possible MITM)")
        cred = Credential(token=token)
        api = cluster.api
        csr = CertificateSigningRequest(
            name=f"node-csr-{node_name}",
            cn=f"system:node:{node_name}", orgs=["system:nodes"])
        # create through the chain: the registry stamps requestor/groups
        # from the authenticated bootstrap identity
        api.create("CertificateSigningRequest", csr, cred=cred)

        # the controller pair: auto-approve (bootstrap requestor + node
        # shape) then sign with the cluster CA
        from kubernetes_tpu.client.informer import SharedInformerFactory
        from kubernetes_tpu.controllers.certificates import (
            CSRApprovingController,
            CSRSigningController,
        )
        factory = SharedInformerFactory(api.store)
        approving = CSRApprovingController(api.store, factory,
                                           record_events=False)
        signing = CSRSigningController(api.store, factory, cluster.ca,
                                       record_events=False)
        factory.start()
        factory.step_all()
        approving.sync(csr.name)
        signing.sync(csr.name)
        issued = api.store.get("CertificateSigningRequest", "", csr.name)
        if issued.certificate is None:
            raise SystemExit(
                f"error: CSR {csr.name} was not issued "
                f"(approved={issued.approved}, denied={issued.denied})")
        node_cred = Credential(cert=issued.certificate)
        node = make_node(node_name, cpu=cpu, memory=memory)
        try:
            api.create("Node", node, cred=node_cred)
        except Conflict:
            pass
        self._print(f"[join] node {node_name} joined the cluster")
        return node_cred

    # ----------------------------------------------------------------- token

    def token_list(self, cluster: InitResult) -> List[str]:
        rows = []
        for s in cluster.api.store.list("Secret")[0]:
            if s.namespace == "kube-system" \
                    and s.name.startswith("bootstrap-token-"):
                tid = s.data.get("token-id", "")
                exp = float(s.data.get("expiration", "0"))
                ttl = max(0, int(exp - self._now()))
                rows.append(f"{tid}.<redacted>  ttl={ttl}s")
        for r in rows:
            self._print(r)
        if not rows:
            self._print("no bootstrap tokens")
        return rows

    def token_create(self, cluster: InitResult,
                     ttl: float = 86400.0) -> str:
        tok = self.phase_bootstrap_token(cluster.api, cluster.bootstrap,
                                         ttl=ttl)
        self._print(tok)
        return tok

    def token_delete(self, cluster: InitResult, token_id: str) -> None:
        cluster.bootstrap.revoke(token_id)
        try:
            cluster.api.store.delete("Secret", "kube-system",
                                     f"bootstrap-token-{token_id}")
        except NotFound:
            raise SystemExit(f"error: token {token_id!r} not found")
        self._print(f"bootstrap token {token_id!r} deleted")

    # ----------------------------------------------------------------- reset

    def reset(self, workdir: str) -> None:
        """kubeadm reset: tear the on-disk phase artifacts down."""
        import shutil
        for sub in ("pki", "manifests"):
            shutil.rmtree(os.path.join(workdir, sub), ignore_errors=True)
        for comp in ("admin", "controller-manager", "scheduler"):
            try:
                os.unlink(os.path.join(workdir, comp + ".conf"))
            except FileNotFoundError:
                pass
        self._print(f"[reset] cleaned {workdir}")
