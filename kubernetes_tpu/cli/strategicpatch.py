"""Strategic merge patch + the 3-way apply merge.

The reference's apply is NOT a PUT of the manifest: kubectl computes a
three-way strategic merge patch from (last-applied config, new manifest,
live object) — deletions come from last-applied vs new, additions/updates
from new vs live, and everything the user's manifest never mentioned (fields
set by controllers: replicas under HPA, status, server defaults) survives
(pkg/kubectl/cmd/apply.go:658 Patch,
staging/src/k8s.io/apimachinery/pkg/util/strategicpatch/patch.go).

"Strategic" = lists are not JSON-patch atomic: fields carrying a
patchMergeKey struct tag merge per-item by that key (containers by name,
ports by containerPort, env by name — types.go patchMergeKey tags); lists
without a merge key replace atomically. A `$patch: delete` directive inside
a merge-keyed item deletes it (patch.go directive handling).

Operates on manifest-shaped dicts (the CLI's YAML surface, api/wire.py);
MERGE_KEYS centralizes what the reference expresses as struct tags."""

from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional

# field name -> merge-key candidates (the patchMergeKey struct tags of the
# modeled manifest surface). Candidates cover both spellings the two wire
# shapes use (native flat snake_case vs the Pod/Node metadata/spec shape's
# camelCase); _pick_merge_key selects whichever the items carry.
MERGE_KEYS: Dict[str, tuple] = {
    "containers": ("name",),
    "volumes": ("name",),
    "env": ("name",),
    "ports": ("container_port", "containerPort"),
    "tolerations": ("key",),
    "conditions": ("type",),
}

PATCH_DIRECTIVE = "$patch"
DELETE = "delete"
REPLACE = "replace"


def _merge_key_for(field: str, *item_lists: List) -> Optional[str]:
    cands = MERGE_KEYS.get(field)
    if not cands:
        return None
    for cand in cands:
        for items in item_lists:
            if any(isinstance(i, dict) and cand in i for i in items):
                return cand
    return cands[0]


def _index_by(items: List[dict], key: str) -> Dict[Any, dict]:
    out = {}
    for it in items:
        if isinstance(it, dict) and key in it:
            out[it[key]] = it
    return out


def strategic_merge_patch(current: Any, patch: Any,
                          field: str = "") -> Any:
    """Apply `patch` onto `current` (the 2-way half; patch.go
    mergeMap/mergeSlice):

    - maps merge recursively; a None value deletes the key
    - merge-keyed lists merge per item by key; `$patch: delete` removes
      the keyed item; unmatched patch items append
    - un-keyed lists (and scalar/type mismatches) replace atomically
    """
    if isinstance(current, dict) and isinstance(patch, dict):
        if patch.get(PATCH_DIRECTIVE) == REPLACE:
            out = {k: copy.deepcopy(v) for k, v in patch.items()
                   if k != PATCH_DIRECTIVE}
            return out
        out = copy.deepcopy(current)
        for k, v in patch.items():
            if v is None:
                out.pop(k, None)
            elif k in out:
                out[k] = strategic_merge_patch(out[k], v, field=k)
            else:
                out[k] = copy.deepcopy(v)
        return out
    if isinstance(current, list) and isinstance(patch, list):
        key = _merge_key_for(field, patch, current)
        if key is None or not all(isinstance(i, dict) for i in patch):
            return copy.deepcopy(patch)  # atomic replace
        out = [copy.deepcopy(i) for i in current]
        by_key = {i.get(key): idx for idx, i in enumerate(out)
                  if isinstance(i, dict)}
        for item in patch:
            k = item.get(key)
            if item.get(PATCH_DIRECTIVE) == DELETE:
                out = [i for i in out
                       if not (isinstance(i, dict) and i.get(key) == k)]
                by_key = {i.get(key): idx for idx, i in enumerate(out)
                          if isinstance(i, dict)}
                continue
            if k in by_key:
                out[by_key[k]] = strategic_merge_patch(
                    out[by_key[k]], item, field=field)
            else:
                out.append(copy.deepcopy(item))
                by_key[k] = len(out) - 1
        return out
    return copy.deepcopy(patch)


def create_two_way_diff(original: Any, modified: Any,
                        field: str = "") -> Any:
    """The patch that turns `original` into `modified`
    (CreateTwoWayMergePatch): changed/added keys appear; keys in original
    missing from modified appear as None (deletion); merge-keyed list
    items removed from the manifest become `$patch: delete` entries."""
    if isinstance(original, dict) and isinstance(modified, dict):
        patch: Dict[str, Any] = {}
        for k, v in modified.items():
            if k not in original:
                patch[k] = copy.deepcopy(v)
            elif original[k] != v:
                sub = create_two_way_diff(original[k], v, field=k)
                if sub is not _UNCHANGED:
                    patch[k] = sub
        for k in original:
            if k not in modified:
                patch[k] = None
        return patch if patch else _UNCHANGED
    if isinstance(original, list) and isinstance(modified, list):
        key = _merge_key_for(field, original, modified)
        if key is None or not (
                all(isinstance(i, dict) for i in original)
                and all(isinstance(i, dict) for i in modified)):
            return copy.deepcopy(modified) \
                if original != modified else _UNCHANGED
        orig_by = _index_by(original, key)
        mod_by = _index_by(modified, key)
        items: List[dict] = []
        for item in modified:
            k = item.get(key)
            if k in orig_by:
                sub = create_two_way_diff(orig_by[k], item, field=field)
                if sub is not _UNCHANGED:
                    sub = dict(sub) if isinstance(sub, dict) else {}
                    sub[key] = k
                    items.append(sub)
            else:
                items.append(copy.deepcopy(item))
        for k in orig_by:
            if k not in mod_by:
                items.append({key: k, PATCH_DIRECTIVE: DELETE})
        return items if items else _UNCHANGED
    return copy.deepcopy(modified) if original != modified else _UNCHANGED


class _Unchanged:
    def __repr__(self):
        return "<unchanged>"


_UNCHANGED = _Unchanged()


def _diff_deletions_only(original: Any, modified: Any,
                         field: str = "") -> Any:
    """The deletions half of CreateThreeWayMergePatch (patch.go:1958
    diffMaps with IgnoreChangesAndAdditions): ONLY the keys/list items
    present in `original` but absent from `modified` — null markers and
    `$patch: delete` entries, recursing for nested deletions."""
    if isinstance(original, dict) and isinstance(modified, dict):
        patch: Dict[str, Any] = {}
        for k, v in original.items():
            if k not in modified:
                patch[k] = None
            else:
                sub = _diff_deletions_only(v, modified[k], field=k)
                if sub is not _UNCHANGED:
                    patch[k] = sub
        return patch if patch else _UNCHANGED
    if isinstance(original, list) and isinstance(modified, list):
        key = _merge_key_for(field, original, modified)
        if key is None or not (
                all(isinstance(i, dict) for i in original)
                and all(isinstance(i, dict) for i in modified)):
            return _UNCHANGED  # atomic lists replace via the delta diff
        mod_by = _index_by(modified, key)
        items: List[dict] = []
        for k, item in _index_by(original, key).items():
            if k not in mod_by:
                items.append({key: k, PATCH_DIRECTIVE: DELETE})
            else:
                sub = _diff_deletions_only(item, mod_by[k], field=field)
                if sub is not _UNCHANGED:
                    sub = dict(sub)
                    sub[key] = k
                    items.append(sub)
        return items if items else _UNCHANGED
    return _UNCHANGED


def _diff_ignore_deletions(current: Any, modified: Any,
                           field: str = "") -> Any:
    """The delta half of CreateThreeWayMergePatch (diffMaps with
    IgnoreDeletions): additions and UPDATES that bring `current` to
    `modified`, with no null markers — so live drift on manifest-specified
    fields is reverted, while fields only the server/controllers own (absent
    from `modified`) survive."""
    if isinstance(current, dict) and isinstance(modified, dict):
        patch: Dict[str, Any] = {}
        for k, v in modified.items():
            if k not in current:
                patch[k] = copy.deepcopy(v)
            elif current[k] != v:
                sub = _diff_ignore_deletions(current[k], v, field=k)
                if sub is not _UNCHANGED:
                    patch[k] = sub
        return patch if patch else _UNCHANGED
    if isinstance(current, list) and isinstance(modified, list):
        key = _merge_key_for(field, current, modified)
        if key is None or not (
                all(isinstance(i, dict) for i in current)
                and all(isinstance(i, dict) for i in modified)):
            return copy.deepcopy(modified) \
                if current != modified else _UNCHANGED
        cur_by = _index_by(current, key)
        items: List[dict] = []
        for item in modified:
            k = item.get(key)
            if k in cur_by:
                sub = _diff_ignore_deletions(cur_by[k], item, field=field)
                if sub is not _UNCHANGED:
                    sub = dict(sub) if isinstance(sub, dict) else {}
                    sub[key] = k
                    items.append(sub)
            else:
                items.append(copy.deepcopy(item))
        return items if items else _UNCHANGED
    return copy.deepcopy(modified) if current != modified else _UNCHANGED


def three_way_merge(original: Any, modified: Any, current: Any,
                    modified_for_delta: Any = None) -> Any:
    """Apply's merge (CreateThreeWayMergePatch, patch.go:1958, + apply):
    the patch is the union of

      1. deletions from diff(original, modified) — fields/list items the
         user's manifest dropped since last-applied, and
      2. additions/updates from diff(current, modified) IGNORING deletions
         — so a field the manifest specifies is driven to the manifest's
         value even when the LIVE object drifted (a controller or manual
         edit changed it) while last-applied matches the manifest,

    played onto the LIVE object — fields the manifest never managed
    (controller writes, server defaults) pass through untouched.

    modified_for_delta: optional narrower view of `modified` for the delta
    half — callers whose canonical encoding materializes DEFAULTS for
    fields the user never wrote (decode->encode normalization) pass the
    projection onto the manifest's actual keys here, the analog of
    kubectl computing `modified` from the FILE bytes
    (GetModifiedConfiguration) rather than a round-tripped object; without
    it the delta would 'revert' server-owned fields to defaults."""
    deletions = _diff_deletions_only(original or {}, modified or {})
    delta = _diff_ignore_deletions(
        current or {},
        (modified_for_delta if modified_for_delta is not None
         else modified) or {})
    if deletions is _UNCHANGED and delta is _UNCHANGED:
        return copy.deepcopy(current)
    if deletions is _UNCHANGED:
        patch = delta
    elif delta is _UNCHANGED:
        patch = deletions
    else:
        # per-key disjoint by construction (a key deleted from `modified`
        # cannot also appear in the delta), so the merge is a plain overlay
        patch = strategic_merge_patch(deletions, delta)
    return strategic_merge_patch(current, patch)
