"""Authorizers: (user, verb, resource, ...) -> allow / no-opinion / deny.

Mirror of the reference's authorization modes
(pkg/kubeapiserver/authorizer/config.go: union of Node, ABAC, RBAC, webhook,
AlwaysAllow/AlwaysDeny — first authorizer with an opinion wins):

- RBAC:  plugin/pkg/auth/authorizer/rbac/rbac.go RBACAuthorizer.Authorize —
  visit all ClusterRoleBindings + namespace RoleBindings applying to the
  user, match rules by verb/apiGroup/resource/resourceName.
- Node:  plugin/pkg/auth/authorizer/node/node_authorizer.go — kubelets
  (group system:nodes, user system:node:<name>) restricted to their own
  Node object/status and to secrets/configmaps/PV/PVCs of pods bound to
  them (modeled via the api store's pod index).
- ABAC:  pkg/auth/authorizer/abac/abac.go — ordered policy list.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.api.rbac import (
    ClusterRole,
    ClusterRoleBinding,
    NODES_GROUP,
    Role,
    RoleBinding,
    UserInfo,
)

ALLOW = "allow"
DENY = "deny"
NO_OPINION = "no-opinion"


@dataclass
class Attributes:
    """authorizer.Attributes (apiserver/pkg/authorization/authorizer)."""

    user: UserInfo
    verb: str  # get|list|watch|create|update|patch|delete|...
    resource: str = ""  # plural, e.g. "pods" or "pods/binding"
    namespace: str = ""
    name: str = ""
    api_group: str = ""
    path: str = ""  # non-resource request path

    @property
    def is_resource_request(self) -> bool:
        return bool(self.resource)


class Forbidden(Exception):
    """Authorization denied (403)."""


class RBACAuthorizer:
    """Rule resolution over role/binding objects kept in the API store (the
    informer-backed registries of rbac.go's RoleGetter et al.)."""

    def __init__(self, store):
        # store must expose list(kind) -> (objects, rv)
        self._store = store

    def _roles_for(self, user: UserInfo, namespace: str):
        crbs, _ = self._store.list("ClusterRoleBinding")
        rbs, _ = self._store.list("RoleBinding")
        crs = {r.name: r for r in self._store.list("ClusterRole")[0]}
        rs = {(r.namespace, r.name): r
              for r in self._store.list("Role")[0]}
        for b in crbs:
            if b.role_ref and self._subject_matches(b.subjects, user, ""):
                role = crs.get(b.role_ref.name) \
                    if b.role_ref.kind == "ClusterRole" else None
                if role is not None:
                    yield role.rules, ""  # cluster-wide
        if namespace:
            for b in rbs:
                if b.namespace != namespace or not b.role_ref:
                    continue
                if not self._subject_matches(b.subjects, user, namespace):
                    continue
                if b.role_ref.kind == "ClusterRole":
                    role = crs.get(b.role_ref.name)
                    rules = role.rules if role else None
                else:
                    role = rs.get((namespace, b.role_ref.name))
                    rules = role.rules if role else None
                if rules is not None:
                    yield rules, namespace

    @staticmethod
    def _subject_matches(subjects, user: UserInfo, namespace: str) -> bool:
        for s in subjects:
            if s.kind == "User" and s.name == user.name:
                return True
            if s.kind == "Group" and s.name in user.groups:
                return True
            if s.kind == "ServiceAccount":
                sa_user = f"system:serviceaccount:{s.namespace or namespace}:{s.name}"
                if user.name == sa_user:
                    return True
        return False

    def authorize(self, attrs: Attributes) -> str:
        for rules, scope in self._roles_for(attrs.user, attrs.namespace):
            for rule in rules:
                if not attrs.is_resource_request:
                    if rule.matches_verb(attrs.verb) \
                            and rule.matches_non_resource_url(attrs.path):
                        return ALLOW
                    continue
                if (rule.matches_verb(attrs.verb)
                        and (not rule.api_groups
                             or "*" in rule.api_groups
                             or attrs.api_group in rule.api_groups)
                        and rule.matches_resource(attrs.resource)
                        and rule.matches_name(attrs.name)):
                    return ALLOW
        return NO_OPINION


class NodeAuthorizer:
    """Kubelet identity system:node:<name> limited to its own objects
    (plugin/pkg/auth/authorizer/node/node_authorizer.go): its Node + status,
    pods bound to it, and — for secrets/configmaps/PVCs/PVs — only GET of a
    NAMED object reachable from a pod bound to this node (the reference walks
    its graph of pod->secret/configmap/pvc->pv edges; here we walk the
    store's pod objects directly). Everything out of scope is NO_OPINION, not
    DENY, so a node identity that also holds other role bindings still gets
    RBAC's verdict (union semantics, node_authorizer.go:77-103)."""

    READ_VERBS = ("get", "list", "watch")

    def __init__(self, store):
        self._store = store
        # per-node reference-set cache keyed by store rv — the poor man's
        # node/graph.go: rebuilt only when the store has moved, so repeated
        # secret gets by the same kubelet don't rescan the pod table
        self._ref_cache: Dict[str, Tuple[int, set]] = {}

    def authorize(self, attrs: Attributes) -> str:
        user = attrs.user
        if NODES_GROUP not in user.groups \
                or not user.name.startswith("system:node:"):
            return NO_OPINION
        node_name = user.name[len("system:node:"):]
        res = attrs.resource
        if res in ("nodes", "nodes/status"):
            if attrs.name in ("", node_name):
                return ALLOW
            return NO_OPINION  # another node's object: leave it to RBAC
        if res in ("pods", "pods/status"):
            if attrs.verb in self.READ_VERBS:
                return ALLOW
            if not attrs.name:
                return NO_OPINION  # writes need a named pod
            pod = self._get("Pod", attrs.namespace, attrs.name)
            if pod is not None and getattr(pod, "node_name", "") == node_name:
                return ALLOW
            return NO_OPINION
        if res in ("secrets", "configmaps",
                   "persistentvolumeclaims", "persistentvolumes"):
            # only get-by-name, and only when a pod bound to this node
            # references the object (node_authorizer.go authorizeGet)
            if attrs.verb != "get" or not attrs.name:
                return NO_OPINION
            if self._reachable(res, attrs.namespace, attrs.name, node_name):
                return ALLOW
            return NO_OPINION
        if res in ("services", "endpoints"):
            if attrs.verb in self.READ_VERBS:
                return ALLOW
            return NO_OPINION
        if res == "events":
            if attrs.verb in ("create", "update", "patch"):
                return ALLOW
            return NO_OPINION
        return NO_OPINION

    def _reachable(self, res: str, ns: str, name: str, node: str) -> bool:
        """Is the named object referenced by any pod bound to `node`?
        (the graph edges of node/graph.go, walked into a cached per-node
        reference set, invalidated whenever the store rv moves)"""
        return (res, ns, name) in self._refs(node)

    def _refs(self, node: str) -> set:
        from kubernetes_tpu.api.types import VolumeKind
        try:
            pods, rv = self._store.list("Pod")
        except Exception:
            return set()
        cached = self._ref_cache.get(node)
        if cached is not None and cached[0] == rv:
            return cached[1]
        kind_res = {VolumeKind.SECRET: "secrets",
                    VolumeKind.CONFIG_MAP: "configmaps",
                    VolumeKind.PVC: "persistentvolumeclaims"}
        refs: set = set()
        for pod in pods:
            if getattr(pod, "node_name", "") != node:
                continue
            pod_ns = getattr(pod, "namespace", "")
            for vol in getattr(pod, "volumes", None) or []:
                res = kind_res.get(vol.kind)
                if res is None:
                    continue
                refs.add((res, pod_ns, vol.volume_id))
                if vol.kind == VolumeKind.PVC:
                    pvc = self._get("PersistentVolumeClaim", pod_ns,
                                    vol.volume_id)
                    if pvc is not None and getattr(pvc, "volume_name", ""):
                        refs.add(("persistentvolumes", "", pvc.volume_name))
        # prune entries from older store revisions so the cache tracks only
        # the live rv (bounded by the node count)
        self._ref_cache = {n: v for n, v in self._ref_cache.items()
                           if v[0] == rv}
        self._ref_cache[node] = (rv, refs)
        return refs

    def _get(self, kind, ns, name):
        try:
            return self._store.get(kind, ns, name)
        except Exception:
            return None


@dataclass
class ABACPolicy:
    """pkg/apis/abac v1beta1 Policy line."""

    user: str = ""
    group: str = ""
    verb: str = "*"
    resource: str = "*"
    namespace: str = "*"
    readonly: bool = False


class ABACAuthorizer:
    """Ordered policy-file authorizer (pkg/auth/authorizer/abac)."""

    READ_VERBS = ("get", "list", "watch")

    def __init__(self, policies: List[ABACPolicy]):
        self.policies = list(policies)

    def authorize(self, attrs: Attributes) -> str:
        for p in self.policies:
            if p.user and p.user != "*" and p.user != attrs.user.name:
                continue
            if p.group and p.group != "*" and p.group not in attrs.user.groups:
                continue
            if p.readonly and attrs.verb not in self.READ_VERBS:
                continue
            if p.verb != "*" and p.verb != attrs.verb:
                continue
            if p.resource != "*" and p.resource != attrs.resource:
                continue
            if p.namespace != "*" and p.namespace != attrs.namespace:
                continue
            return ALLOW
        return NO_OPINION


class AlwaysAllowAuthorizer:
    def authorize(self, attrs: Attributes) -> str:
        return ALLOW


class AlwaysDenyAuthorizer:
    def authorize(self, attrs: Attributes) -> str:
        return DENY


class WebhookAuthorizer:
    """SubjectAccessReview-over-webhook stand-in: delegate to a callable
    (plugin/pkg/auth/authorizer/webhook)."""

    def __init__(self, fn: Callable[[Attributes], str]):
        self._fn = fn

    def authorize(self, attrs: Attributes) -> str:
        return self._fn(attrs)


class UnionAuthorizer:
    """First authorizer with an opinion wins (union.New)."""

    def __init__(self, authorizers: List):
        self.authorizers = list(authorizers)

    def authorize(self, attrs: Attributes) -> str:
        for a in self.authorizers:
            verdict = a.authorize(attrs)
            if verdict != NO_OPINION:
                return verdict
        return NO_OPINION
