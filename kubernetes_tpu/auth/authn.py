"""Authenticators: request credential -> UserInfo.

Mirror of the reference's authenticator stack
(pkg/kubeapiserver/authenticator/config.go New: union of x509, static token
file, bootstrap token, service-account JWT, OIDC, webhook — each tried in
order, first success wins; staging/src/k8s.io/apiserver/pkg/authentication).
TPU-native simplifications: certificates are modeled as signed identity
records (no X.509 parsing — the trust decision, not the encoding, is what the
control plane semantics need); service-account tokens are HMAC-signed JWTs
built with the stdlib (no external crypto deps in the image).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api.rbac import (
    SERVICE_ACCOUNTS_GROUP,
    SYSTEM_AUTHENTICATED,
    UserInfo,
)


class Unauthenticated(Exception):
    """No authenticator recognized the credential (401)."""


@dataclass
class Credential:
    """What a request presents: a bearer token and/or a client 'certificate'
    (a signed identity record standing in for an x509 client cert)."""

    token: str = ""
    cert: Optional[dict] = None  # {"cn":..., "orgs": [...], "sig": ...}
    # Impersonate-User / Impersonate-Group headers (apiserver/pkg/
    # endpoints/filters/impersonation.go): acted on AFTER authentication,
    # gated by the "impersonate" verb on users/groups
    impersonate_user: str = ""
    impersonate_groups: tuple = ()


class TokenAuthenticator:
    """Static token file (--token-auth-file;
    apiserver/pkg/authentication/token/tokenfile)."""

    def __init__(self, tokens: Dict[str, UserInfo]):
        self._tokens = dict(tokens)

    def authenticate(self, cred: Credential) -> Optional[UserInfo]:
        if cred.token and cred.token in self._tokens:
            return self._tokens[cred.token]
        return None


class BootstrapTokenAuthenticator:
    """kubeadm bootstrap tokens of the form <id>.<secret>
    (plugin/pkg/auth/authenticator/token/bootstrap): authenticates as
    system:bootstrap:<id> in group system:bootstrappers. Tokens are
    registered with an expiry and may be revoked (token cleaner)."""

    GROUP = "system:bootstrappers"

    def __init__(self, now=time.time):
        self._tokens: Dict[str, Tuple[str, float]] = {}  # id -> (secret, exp)
        self._now = now

    def add_token(self, token_id: str, secret: str, ttl: float = 86400.0) -> None:
        self._tokens[token_id] = (secret, self._now() + ttl)

    def revoke(self, token_id: str) -> None:
        self._tokens.pop(token_id, None)

    def expired_ids(self) -> List[str]:
        now = self._now()
        return [tid for tid, (_, exp) in self._tokens.items() if exp <= now]

    def authenticate(self, cred: Credential) -> Optional[UserInfo]:
        if not cred.token or "." not in cred.token:
            return None
        tid, _, secret = cred.token.partition(".")
        entry = self._tokens.get(tid)
        if entry is None:
            return None
        want, exp = entry
        if exp <= self._now() or not hmac.compare_digest(want, secret):
            return None
        return UserInfo("system:bootstrap:" + tid, groups=[self.GROUP])


def _b64(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64(s: str) -> bytes:
    pad = -len(s) % 4
    return base64.urlsafe_b64decode(s + "=" * pad)


class ServiceAccountTokenAuthenticator:
    """Service-account JWTs (pkg/serviceaccount/jwt.go): subject
    system:serviceaccount:<ns>:<name>, groups system:serviceaccounts and
    system:serviceaccounts:<ns>. HS256 HMAC instead of RSA (same claims)."""

    ISSUER = "kubernetes/serviceaccount"

    def __init__(self, signing_key: bytes):
        self._key = signing_key

    def issue(self, namespace: str, name: str, uid: str = "") -> str:
        header = _b64(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
        claims = _b64(json.dumps({
            "iss": self.ISSUER,
            "sub": f"system:serviceaccount:{namespace}:{name}",
            "kubernetes.io/serviceaccount/namespace": namespace,
            "kubernetes.io/serviceaccount/service-account.name": name,
            "kubernetes.io/serviceaccount/service-account.uid": uid,
        }).encode())
        body = header + "." + claims
        sig = _b64(hmac.new(self._key, body.encode(), hashlib.sha256).digest())
        return body + "." + sig

    def authenticate(self, cred: Credential) -> Optional[UserInfo]:
        parts = cred.token.split(".") if cred.token else []
        if len(parts) != 3:
            return None
        body = parts[0] + "." + parts[1]
        want = _b64(hmac.new(self._key, body.encode(), hashlib.sha256).digest())
        if not hmac.compare_digest(want, parts[2]):
            return None
        try:
            claims = json.loads(_unb64(parts[1]))
        except ValueError:
            return None
        if claims.get("iss") != self.ISSUER:
            return None
        ns = claims.get("kubernetes.io/serviceaccount/namespace", "")
        name = claims.get("kubernetes.io/serviceaccount/service-account.name", "")
        if not ns or not name:
            return None
        return UserInfo(
            f"system:serviceaccount:{ns}:{name}",
            groups=[SERVICE_ACCOUNTS_GROUP, SERVICE_ACCOUNTS_GROUP + ":" + ns],
            uid=claims.get("kubernetes.io/serviceaccount/service-account.uid", ""))


class CertAuthenticator:
    """Client-'certificate' authenticator (x509 stand-in,
    apiserver/pkg/authentication/request/x509): the identity record carries
    CN (user) + O (groups) and an HMAC signature by the cluster CA key."""

    def __init__(self, ca_key: bytes):
        self._key = ca_key

    def sign(self, cn: str, orgs: List[str]) -> dict:
        payload = json.dumps({"cn": cn, "orgs": sorted(orgs)}, sort_keys=True)
        sig = hmac.new(self._key, payload.encode(), hashlib.sha256).hexdigest()
        return {"cn": cn, "orgs": sorted(orgs), "sig": sig}

    def authenticate(self, cred: Credential) -> Optional[UserInfo]:
        cert = cred.cert
        if not cert:
            return None
        payload = json.dumps({"cn": cert.get("cn", ""),
                              "orgs": sorted(cert.get("orgs", []))},
                             sort_keys=True)
        want = hmac.new(self._key, payload.encode(), hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want, cert.get("sig", "")):
            return None
        return UserInfo(cert["cn"], groups=list(cert.get("orgs", [])))


class UnionAuthenticator:
    """Try each in order; first success wins; everyone authenticated gains
    system:authenticated (union.New + group adder in the reference)."""

    def __init__(self, authenticators: List):
        self.authenticators = list(authenticators)

    def authenticate(self, cred: Credential) -> UserInfo:
        for a in self.authenticators:
            user = a.authenticate(cred)
            if user is not None:
                if SYSTEM_AUTHENTICATED not in user.groups:
                    user.groups.append(SYSTEM_AUTHENTICATED)
                return user
        raise Unauthenticated("no authenticator recognized the credential")
