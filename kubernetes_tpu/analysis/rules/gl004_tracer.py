"""GL004 — tracer leak out of a traced scope.

Inside a jit-traced function, values are abstract tracers; storing one on
`self`, a global, or any host container outlives the trace and either
poisons later eager code with a `TracerLeakError` far from the cause, or
(worse) silently caches trace-time garbage. Traced scopes are:

- defs decorated `@jax.jit` / `@functools.partial(jax.jit, ...)`;
- defs wrapped at module level (`X = jax.jit(f)` marks `f`);
- nested defs handed to `lax.while_loop` / `lax.scan` / `lax.cond` /
  `vmap` / `grad` etc. INSIDE a traced scope (the `cond`/`body` pair of
  waves_loop) — their bodies trace with the parent.

Flagged inside those scopes (including nested defs):
- any attribute store (`obj.x = ...`, `self.x += ...`);
- any subscript store or mutating-method call (`.append`/`.update`/...)
  whose base name is NOT bound locally in the traced scope — writes into
  module globals or closure state;
- assignments to names declared `global`/`nonlocal`.
"""

from __future__ import annotations

import ast
from typing import List, Set

from kubernetes_tpu.analysis.rules.base import (
    TRACE_CONSUMERS,
    FileContext,
    Finding,
    ProjectIndex,
    _is_jit_expr,
    dotted,
    functions_of,
    last_component,
)

RULE = "GL004"

_CONTAINER_MUTATORS = frozenset({"append", "extend", "add", "update",
                                 "insert", "setdefault", "pop", "remove",
                                 "clear"})


def _local_bindings(fn: ast.AST) -> Set[str]:
    """Names bound inside `fn`: params, assignment targets, for-targets,
    withitems, comprehension targets, nested def/class names."""
    names: Set[str] = set()
    a = fn.args
    for arg in (a.posonlyargs + a.args + a.kwonlyargs
                + ([a.vararg] if a.vararg else [])
                + ([a.kwarg] if a.kwarg else [])):
        names.add(arg.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            names.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            names.add(node.name)
    return names


def _traced_functions(ctx: FileContext, index: ProjectIndex):
    """Traced scopes in this file — nested defs handed to
    lax.while_loop/scan/... inside a traced scope need no separate entry
    (ast.walk over the parent already covers their bodies); TRACE_CONSUMERS
    membership exists so helpers traced OUTSIDE any jit (a bare vmap at
    module level) still get a scope of their own. One tree walk total."""
    consumed = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            fname = dotted(node.func)
            if fname and last_component(fname) in TRACE_CONSUMERS:
                consumed.update(a.id for a in node.args
                                if isinstance(a, ast.Name))
    out = []
    for fn in functions_of(ctx.tree):
        if any(_is_jit_expr(d) for d in fn.decorator_list):
            out.append(fn)
        elif fn.name in (index.traced_defs | consumed) \
                and ctx.enclosing_function(fn) is None:
            out.append(fn)
    return out


def check(ctx: FileContext, index: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    for fn in _traced_functions(ctx, index):
        local = _local_bindings(fn)
        declared: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.Global, ast.Nonlocal)):
                declared.update(node.names)
        for node in ast.walk(fn):
            tgt = None
            kind = None
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, ast.Attribute):
                        tgt = dotted(t) or f"<expr>.{t.attr}"
                        kind = "attribute store"
                    elif isinstance(t, ast.Subscript):
                        base = t.value
                        p = dotted(base)
                        root = p.partition(".")[0] if p else None
                        if root is not None and root not in local:
                            tgt = f"{p}[...]"
                            kind = "subscript store into non-local"
                    elif isinstance(t, ast.Name) and t.id in declared:
                        tgt = t.id
                        kind = "global/nonlocal store"
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _CONTAINER_MUTATORS:
                p = dotted(node.func.value)
                root = p.partition(".")[0] if p else None
                if root is not None and root not in local:
                    tgt = f"{p}.{node.func.attr}(...)"
                    kind = "container mutation of non-local"
            if tgt is not None:
                findings.append(Finding(
                    RULE, ctx.path, node.lineno, node.col_offset,
                    f"{kind} ({tgt}) inside traced scope "
                    f"'{fn.name}' — a tracer stored here outlives the "
                    "trace (leak) and the side effect replays only at "
                    "trace time; return the value through the carry "
                    "instead",
                    context=ctx.qualname(fn)))
    return findings
