"""GL007 — torn read/write: guarded-field access outside the guard.

The r12 "metrics torn-read audit" class, machine-checked: a class keeps
a field's writers under `with self._lock:` so multi-word updates commit
atomically (count and sum advance together; a deque mutates while a
scrape iterates) — and then one accessor reads the field bare, seeing a
half-committed update. The audit that caught Histogram's (count, sum)
tear was a hand pass; this rule is that pass, run on every file forever.

Per class that owns at least one lock attribute: a field QUALIFIES as
lock-guarded when at least one write runs under the class's own lock and
guarded writes are not outnumbered by unguarded ones ("predominantly
guarded" — one stray write must not demote the field, it IS the bug).
Every access (read or write) to a qualifying field outside any lock
region then fires. Guarded contexts:

- lexically inside `with self.<lock>:` for any lock attr of the class;
- a method named `*_locked` — the repo's caller-holds-the-lock
  convention (the runtime half verifies it: those helpers carry
  `lockcheck.assert_held`, checked under GRAFT_LOCKCHECK=1);
- `__init__`/`__new__`, where no second thread can hold a reference yet
  (accesses there are also never REPORTED, same reasoning).

Single-threaded-by-design accessors (a loop-owned field that shares a
name, a stats read that tolerates staleness) carry
`# graftlint: torn-ok` naming why the tear cannot happen or cannot hurt.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from kubernetes_tpu.analysis.rules.base import (
    MUTATING_METHODS,
    FileContext,
    Finding,
    ProjectIndex,
    class_lock_attrs,
    dotted,
)

RULE = "GL007"

_BIRTH_METHODS = ("__init__", "__new__")


def _method_of(ctx: FileContext, node: ast.AST, klass: ast.ClassDef):
    """The OUTERMOST function between `node` and `klass` — the method
    whose name carries the _locked / __init__ conventions even when the
    access sits in a nested helper."""
    method = None
    for anc in ctx.ancestors(node):
        if anc is klass:
            break
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            method = anc
    return method


def _under_lock(ctx: FileContext, node: ast.AST, klass: ast.ClassDef,
                locks: Dict[str, str]) -> bool:
    for anc in ctx.ancestors(node):
        if anc is klass:
            break
        if isinstance(anc, ast.With):
            for item in anc.items:
                p = dotted(item.context_expr)
                if p is not None and p.startswith("self.") \
                        and p.split(".", 1)[1] in locks:
                    return True
    return False


def _is_write(ctx: FileContext, attr: ast.Attribute) -> bool:
    if isinstance(attr.ctx, (ast.Store, ast.Del)):
        return True
    parent = ctx.parent(attr)
    if isinstance(parent, ast.Subscript) \
            and isinstance(parent.ctx, (ast.Store, ast.Del)):
        return True  # self.f[i] = v / del self.f[k] / self.f[i] += v
    if isinstance(parent, ast.Attribute) \
            and parent.attr in MUTATING_METHODS:
        gp = ctx.parent(parent)
        if isinstance(gp, ast.Call) and gp.func is parent:
            return True  # self.f.append(v) and friends
    return False


def check(ctx: FileContext, index: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    for klass in ast.walk(ctx.tree):
        if not isinstance(klass, ast.ClassDef):
            continue
        locks = class_lock_attrs(klass)
        if not locks:
            continue
        # accesses[field] = [(attr node, is_write, guarded, in_birth)]
        accesses: Dict[str, List[Tuple[ast.Attribute, bool, bool, bool]]] \
            = {}
        for node in ast.walk(klass):
            if not isinstance(node, ast.Attribute):
                continue
            if not (isinstance(node.value, ast.Name)
                    and node.value.id == "self"):
                continue
            field = node.attr
            if field in locks:
                continue
            method = _method_of(ctx, node, klass)
            if method is None:
                continue  # class-level statement, construction-time
            in_birth = method.name in _BIRTH_METHODS
            guarded = (in_birth or method.name.endswith("_locked")
                       or _under_lock(ctx, node, klass, locks))
            accesses.setdefault(field, []).append(
                (node, _is_write(ctx, node), guarded, in_birth))

        for field, acc in sorted(accesses.items()):
            wg = sum(1 for _n, w, g, b in acc if w and g and not b)
            wu = sum(1 for _n, w, g, _b in acc if w and not g)
            if wg < 1 or wu > wg:
                continue  # not a (predominantly) lock-guarded field
            for node, is_write, guarded, in_birth in acc:
                if guarded or in_birth:
                    continue
                kind = "write to" if is_write else "read of"
                findings.append(Finding(
                    RULE, ctx.path, node.lineno, node.col_offset,
                    f"torn {kind} lock-guarded field self.{field}: "
                    f"writes in {klass.name} run under the class lock, "
                    "but this access holds none — it can observe (or "
                    "commit) a half-applied update; take the lock, move "
                    "it into a *_locked helper, or bless a benign race "
                    "with `# graftlint: torn-ok`",
                    context=ctx.qualname(node) or klass.name))
    return findings
