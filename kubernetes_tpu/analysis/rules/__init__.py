"""graftlint rule registry: GL001-GL009, one module each.

A rule module exports `RULE` (the id) and `check(ctx, index) -> [Finding]`,
plus an optional `prepare(contexts, index)` hook run after pass 1 over the
WHOLE linted set (GL006 uses it to build the project-wide lock graph).
The engine (analysis/lint.py) applies pragma suppression and baselines;
rules only report.
"""

from kubernetes_tpu.analysis.rules import (  # noqa: F401
    gl001_aliasing,
    gl002_hostsync,
    gl003_recompile,
    gl004_tracer,
    gl005_generation,
    gl006_lockorder,
    gl007_tornread,
    gl008_blockloop,
    gl009_spawnsafety,
)
from kubernetes_tpu.analysis.rules.base import (  # noqa: F401
    FileContext,
    Finding,
    ProjectIndex,
)

ALL_RULES = (gl001_aliasing, gl002_hostsync, gl003_recompile,
             gl004_tracer, gl005_generation, gl006_lockorder,
             gl007_tornread, gl008_blockloop, gl009_spawnsafety)

RULE_IDS = tuple(m.RULE for m in ALL_RULES)

CATALOG = {
    "GL001": "aliasing upload: jnp.asarray of an in-place-mutated host "
             "buffer / broken copy-required seam",
    "GL002": "hidden device->host sync on a device value in the hot path",
    "GL003": "recompile hazard: jit built in a function/loop, ragged "
             "shapes into a jitted call in a loop",
    "GL004": "tracer leak: host state mutated inside a traced scope",
    "GL005": "snapshot dynamic-row write without dirty/generation bump",
    "GL006": "lock-order cycle / self-deadlock over the project-wide "
             "acquisition graph (declare with lock-order(...))",
    "GL007": "torn read/write: lock-guarded field accessed outside "
             "any lock region",
    "GL008": "blocking call (sleep, threading lock, socket op, device "
             "sync) on an asyncio event-loop thread",
    "GL009": "spawn-unsafe Process target: closure/bound-method "
             "entrypoint or module-global mutable/lock/device capture",
}
