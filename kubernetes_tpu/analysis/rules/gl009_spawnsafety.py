"""GL009 — spawn-context hygiene for multiprocess worker entrypoints.

The r18 fleet runs M full scheduler PROCESSES: each worker is spawned
(never forked — a forked child inherits the parent's jax runtime state
and locks mid-flight) and must build its OWN world from the picklable
config it is handed. The failure modes are all silent-until-production:

- a worker reading a module-level MUTABLE binding (dict/list/set) sees
  the child's import-time copy, not the parent's live state — the two
  diverge without an error anywhere;
- `global X` writes in a worker mutate the CHILD's module only; the
  parent keeps its value and the "shared" state quietly forks;
- a worker closing over a module-level LOCK synchronizes nothing: the
  child gets its own unlocked copy (and under spawn, pickling a live
  lock in the config is a crash at start);
- a worker touching a module-level DEVICE value (a jitted callable's
  module-level result, a jnp array) drags the parent's accelerator
  context across the process boundary;
- a bound-method target (`Process(target=self.run)`) pickles the WHOLE
  owner — including every lock attribute it carries — under spawn, and
  shares them for-real under fork: both wrong;
- a nested def / lambda target is not picklable under spawn at all.

Fires on `Process(target=...)` call sites and on the named entrypoint's
offending reads (module constants — ints, strings, tuples, compiled
regexes — are fine; the rule flags only provably mutable/lock/device
bindings). A worker that genuinely wants a module global (a fork-only
tool, a read-only table mutated nowhere) carries
`# graftlint: spawn-ok` naming why the divergence cannot happen.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from kubernetes_tpu.analysis.rules.base import (
    FileContext,
    Finding,
    ProjectIndex,
    dotted,
    last_component,
    lock_ctor_kind,
)

RULE = "GL009"

_MUTABLE_LITERALS = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                     ast.DictComp, ast.SetComp)


def _module_hazards(tree: ast.Module, index: ProjectIndex
                    ) -> Dict[str, str]:
    """name -> hazard description for module-level bindings a spawn
    worker must not rely on."""
    out: Dict[str, str] = {}
    for stmt in tree.body:
        if not isinstance(stmt, ast.Assign):
            continue
        val = stmt.value
        why: Optional[str] = None
        if isinstance(val, _MUTABLE_LITERALS):
            why = "module-level mutable state (child gets a copy)"
        elif lock_ctor_kind(val) is not None:
            why = "module-level lock (synchronizes nothing across " \
                  "processes)"
        elif isinstance(val, ast.Call):
            fn = dotted(val.func)
            if fn is not None and (
                    fn.startswith(("jnp.", "jax.", "jax.numpy."))
                    or last_component(fn) in index.jitted_names):
                why = "module-level device value (drags the parent's " \
                      "accelerator context across the spawn boundary)"
        if why is None:
            continue
        for t in stmt.targets:
            if isinstance(t, ast.Name):
                out[t.id] = why
    return out


def _bound_names(fn: ast.AST) -> Set[str]:
    """Names bound locally in `fn` (params, assignments, imports, defs,
    comprehension targets, with/except aliases) — everything else a
    worker loads is a free name resolved in the (child's) module."""
    bound: Set[str] = set()
    args = fn.args
    for a in (args.posonlyargs + args.args + args.kwonlyargs
              + ([args.vararg] if args.vararg else [])
              + ([args.kwarg] if args.kwarg else [])):
        bound.add(a.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) \
                and isinstance(node.ctx, (ast.Store, ast.Del)):
            bound.add(node.id)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                bound.add((alias.asname or alias.name).split(".")[0])
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            bound.add(node.name)
        elif isinstance(node, ast.ExceptHandler) and node.name:
            bound.add(node.name)
    # a `global X` makes every X a MODULE reference — X is then free no
    # matter how many local stores exist (and those stores are the
    # child-only divergence GL009 flags)
    for node in ast.walk(fn):
        if isinstance(node, ast.Global):
            bound -= set(node.names)
    return bound


def _global_writes(fn: ast.AST):
    """(name, store node) for writes through `global` declarations — a
    spawn worker mutating ITS module copy while the parent keeps the
    old value."""
    declared: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Global):
            declared.update(node.names)
    if not declared:
        return
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store) \
                and node.id in declared:
            yield node.id, node


def _process_targets(ctx: FileContext):
    """(call node, target expr) for every `...Process(target=...)` (or
    first-positional-callable Process(...)) call in the file."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = dotted(node.func)
        if fn is None or last_component(fn) != "Process":
            continue
        target = None
        for kw in node.keywords:
            if kw.arg == "target":
                target = kw.value
        if target is not None:
            yield node, target


def check(ctx: FileContext, index: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    module_defs = {stmt.name: stmt for stmt in ctx.tree.body
                   if isinstance(stmt, ast.FunctionDef)}
    hazards = _module_hazards(ctx.tree, index)

    for call, target in _process_targets(ctx):
        qual_site = ctx.qualname(call)
        tpath = dotted(target)
        if isinstance(target, ast.Lambda):
            findings.append(Finding(
                RULE, ctx.path, target.lineno, target.col_offset,
                "Process target is a lambda — not picklable under the "
                "spawn context; make the worker entrypoint a "
                "module-level def handed a picklable config",
                context=qual_site))
            continue
        if tpath is not None and tpath.startswith("self."):
            klass = ctx.enclosing_class(call)
            locks = index.lock_classes.get(klass.name, {}) \
                if klass is not None else {}
            if locks:
                held = ", ".join(sorted(locks))
                findings.append(Finding(
                    RULE, ctx.path, target.lineno, target.col_offset,
                    f"Process target {tpath} is a bound method — spawn "
                    f"pickles the whole {klass.name} including its live "
                    f"lock(s) ({held}); hand a module-level def a "
                    "picklable config instead",
                    context=qual_site))
            continue
        if not isinstance(target, ast.Name):
            continue
        worker = module_defs.get(target.id)
        if worker is None:
            # a def nested in the calling function is a closure: spawn
            # cannot pickle it, and its captured locals silently fork
            for anc_fn in [a for a in ctx.ancestors(call)
                           if isinstance(a, (ast.FunctionDef,
                                             ast.AsyncFunctionDef))]:
                for sub in ast.walk(anc_fn):
                    if isinstance(sub, ast.FunctionDef) \
                            and sub.name == target.id and sub is not anc_fn:
                        findings.append(Finding(
                            RULE, ctx.path, target.lineno,
                            target.col_offset,
                            f"Process target {target.id} is a nested "
                            "def (a closure) — not picklable under the "
                            "spawn context and its captured state forks "
                            "silently under fork; move the entrypoint "
                            "to module level",
                            context=qual_site))
                        break
                else:
                    continue
                break
            continue
        reported: Set[str] = set()
        for name, node in _global_writes(worker):
            if name in reported:
                continue
            reported.add(name)
            findings.append(Finding(
                RULE, ctx.path, node.lineno, node.col_offset,
                f"spawn worker {worker.name} writes module global "
                f"{name}: the write lands in the CHILD's module only — "
                "parent and worker state silently fork; report results "
                "through the worker's queue/pipe instead",
                context=f"{worker.name}"))
        if not hazards:
            continue
        bound = _bound_names(worker)
        for node in ast.walk(worker):
            if not isinstance(node, ast.Name) \
                    or not isinstance(node.ctx, ast.Load):
                continue
            name = node.id
            if name in bound or name not in hazards \
                    or name in reported:
                continue
            reported.add(name)
            findings.append(Finding(
                RULE, ctx.path, node.lineno, node.col_offset,
                f"spawn worker {worker.name} reads {name}: "
                f"{hazards[name]} — pass it through the worker's "
                "picklable config (or justify with `# graftlint: "
                "spawn-ok`)",
                context=f"{worker.name}",
            ))
    return findings
