"""GL002 — hidden device→host sync in the hot path.

Every host touch of a device value (`np.asarray(dev)`, `.item()`,
`float(dev)`, `int(dev)`, `bool(dev)`, `.block_until_ready()`) blocks the
caller until the device drains — on the pipelined drain that forfeits the
whole overlap (the device wait PROFILE_r07 worked to hide), and on the
extender warm path it's a per-request stall. The design budget is ONE
blessed sync per wave (`engine/waves.py` place_waves) plus the harvest's
fetch; everything else must either stay on device or carry a
`# graftlint: sync-ok` pragma naming why the stall is paid.

Detection is dataflow-taint within a function, so it cannot false-positive
on numpy-on-numpy `np.asarray`:

- taint sources: results of calls to KNOWN-JITTED callables (the project
  index collects every `@jax.jit` def and module-level `X = jax.jit(...)`
  bind across the linted set), and the WaveHandle device fields
  (`.packed`, `.state_out`, `.counter_out`, `.committed_out`) whose
  device-ness crosses the dispatch→harvest function boundary;
- taint propagates through subscripts of tainted names;
- a sync-forcer applied to a tainted expression fires.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from kubernetes_tpu.analysis.rules.base import (
    DEVICE_ATTRS,
    SYNC_BUILTINS,
    SYNC_METHODS,
    SYNC_WRAPPERS,
    FileContext,
    Finding,
    ProjectIndex,
    dotted,
    functions_of,
    last_component,
)

RULE = "GL002"


def _taint_events(fn: ast.AST, jitted: Set[str]) -> Dict[str, list]:
    """name -> [(line, producer-or-None)] assignment events in line order.
    producer set = the name now holds a device value (assigned from a
    jitted call); None = any other rebind CLEARS the taint (last-write
    wins — `selected = np.asarray(selected)[:pf]` is the sync itself and
    the name is host numpy afterwards)."""
    events: Dict[str, list] = {}
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign):
            continue
        call = node.value
        producer = None
        if isinstance(call, ast.Call):
            fname = dotted(call.func)
            if fname is not None and last_component(fname) in jitted:
                producer = last_component(fname)
        for t in node.targets:
            elts = t.elts if isinstance(t, ast.Tuple) else [t]
            for e in elts:
                if isinstance(e, ast.Name):
                    events.setdefault(e.id, []).append(
                        (node.lineno, producer))
    for evs in events.values():
        # key on the line alone: two same-line rebinds with mixed producers
        # would make tuple comparison reach the None-vs-str element
        evs.sort(key=lambda ev: ev[0])
    return events


def _taint_of(expr: ast.AST, events: Dict[str, list], at_line: int):
    """Why `expr` is a device value at `at_line`, or None. Subscript
    peels; an attribute chain ending in a WaveHandle device field is
    tainted by contract (device-ness crosses the function boundary)."""
    cur = expr
    while isinstance(cur, ast.Subscript):
        cur = cur.value
    if isinstance(cur, ast.Name) and cur.id in events:
        producer = None
        for line, prod in events[cur.id]:
            if line >= at_line:
                break  # >= : a same-line rebind (`x = np.asarray(x)`) is
                # the sync of the PRIOR value — don't let it untaint itself
            producer = prod
        if producer is not None:
            return f"result of jitted '{producer}'"
    p = dotted(cur)
    if p is not None and "." in p and last_component(p) in DEVICE_ATTRS:
        return f"device field '{p}'"
    return None


def check(ctx: FileContext, index: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    for fn in functions_of(ctx.tree):
        events = _taint_events(fn, index.jitted_names)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted(node.func)
            why = None
            forced_by = None
            if fname in SYNC_WRAPPERS and node.args:
                why = _taint_of(node.args[0], events, node.lineno)
                forced_by = fname
            elif fname in SYNC_BUILTINS and len(node.args) == 1:
                why = _taint_of(node.args[0], events, node.lineno)
                forced_by = f"{fname}()"
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in SYNC_METHODS:
                why = _taint_of(node.func.value, events, node.lineno)
                forced_by = f".{node.func.attr}()"
            if why is not None:
                findings.append(Finding(
                    RULE, ctx.path, node.lineno, node.col_offset,
                    f"{forced_by} forces a device->host sync on {why} — "
                    "a pipeline stall in the hot path; keep it on device "
                    "or bless the stall with `# graftlint: sync-ok`",
                    context=ctx.qualname(fn)))
    return findings
