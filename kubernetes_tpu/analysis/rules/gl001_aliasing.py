"""GL001 — zero-copy aliasing of a mutated host buffer.

The r07/r08 race class: the CPU backend zero-copies aligned numpy uploads,
so `jnp.asarray(buf)` hands the device a VIEW of `buf`; an in-place write
to `buf` while an async wave still reads the alias corrupts placements
silently. Three provable shapes fire:

1. same-function: `jnp.asarray(P)` followed (later in the same function)
   by an in-place mutation of the same dotted path P;
2. class-scoped: `jnp.asarray(P)` in one method of a class while another
   method of the SAME class mutates P in place — the attribute's lifetime
   spans calls, so upload/mutate ordering is not decidable and the alias
   must be assumed live (`enc.committed_nodes` vs the harvest fold was
   exactly this);
3. `# graftlint: copy-required` contract seams: the pragma'd statement
   must upload through a copying constructor (`jnp.array`, `.copy()`,
   `np.ascontiguousarray`, `sanitize.upload_copied`) — a later
   "optimization" to `jnp.asarray` fires the rule instead of shipping the
   r07 race again.

The fix idiom — `jnp.array(...)` / `.copy()` / `sanitize.upload_copied` —
never fires: only `jnp.asarray` of a PLAIN dotted path is ever suspect
(call/subscript args are skipped; advanced indexing already copies).
"""

from __future__ import annotations

import ast
from typing import List

from kubernetes_tpu.analysis.rules.base import (
    FileContext,
    Finding,
    ProjectIndex,
    chain_without_root,
    dotted,
    functions_of,
    local_aliases,
    mutations_in,
    resolve,
)

RULE = "GL001"

_ASARRAY = ("jnp.asarray", "jax.numpy.asarray", "upload_frozen",
            "sanitize.upload_frozen")
_COPYING = ("jnp.array", "jax.numpy.array", "np.array", "numpy.array",
            "np.ascontiguousarray", "numpy.ascontiguousarray",
            "upload_copied", "copy", "deepcopy")


def _asarray_sites(fn, aliases):
    """(resolved dotted path, Call node, spelling) for every zero-copy
    upload of a plain dotted path: jnp.asarray AND sanitize.upload_frozen
    (which is jnp.asarray underneath — with GRAFT_SANITIZE unset nothing
    seals the source, so mutating a frozen-seam buffer is the same silent
    race in production)."""
    out = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and node.args:
            fname = dotted(node.func)
            if fname in _ASARRAY:
                p = resolve(dotted(node.args[0]), aliases)
                if p:
                    out.append((p, node, fname))
    return out


def check(ctx: FileContext, index: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []

    # -- shapes 1 + 2: upload-vs-mutation matching -------------------------
    per_fn = {}
    for fn in functions_of(ctx.tree):
        aliases = local_aliases(fn)
        per_fn[fn] = (_asarray_sites(fn, aliases),
                      mutations_in(fn, aliases))

    for fn, (uploads, muts) in per_fn.items():
        for path, call, spelling in uploads:
            hit = None
            for mpath, mline in muts:
                if mpath == path and mline > call.lineno:
                    hit = (mpath, "later in this function")
                    break
            if hit is None and "." in path:
                klass = ctx.enclosing_class(fn)
                if klass is not None:
                    chain = chain_without_root(path)
                    for ofn, (_u, omuts) in per_fn.items():
                        if ofn is fn or ctx.enclosing_class(ofn) is not klass:
                            continue
                        for mpath, _mline in omuts:
                            if "." in mpath \
                                    and chain_without_root(mpath) == chain:
                                hit = (mpath, f"in {ctx.qualname(ofn)}")
                                break
                        if hit:
                            break
            if hit is not None:
                # no line numbers in the message: it feeds the baseline
                # fingerprint, which must survive unrelated line drift
                mpath, where = hit
                findings.append(Finding(
                    RULE, ctx.path, call.lineno, call.col_offset,
                    f"{spelling}({path}) zero-copy aliases a buffer "
                    f"mutated in place ({mpath} {where}); "
                    "an async wave reading the alias races the write — "
                    "use jnp.array / .copy() / sanitize.upload_copied",
                    context=ctx.qualname(fn)))

    # -- shape 3: copy-required contract seams -----------------------------
    # SIMPLE statements only: a compound statement (def/class/with) spans
    # its whole body, which would smear one seam's pragma over unrelated
    # uploads
    for stmt in ast.walk(ctx.tree):
        if not isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign,
                                 ast.Expr, ast.Return)):
            continue
        lo = stmt.lineno
        hi = stmt.end_lineno or lo
        if "copy-required" not in ctx.tags_for_span(lo, hi):
            continue
        calls = [n for n in ast.walk(stmt) if isinstance(n, ast.Call)]
        bad = [n for n in calls if dotted(n.func) in _ASARRAY]
        names = {dotted(n.func) for n in calls} - {None}
        copying = any(nm in _COPYING or nm.rsplit(".", 1)[-1] in _COPYING
                      for nm in names)
        anchor = ctx.enclosing_function(stmt)
        qual = ctx.qualname(anchor) if anchor is not None else "<module>"
        if bad:
            findings.append(Finding(
                RULE, ctx.path, bad[0].lineno, bad[0].col_offset,
                "copy-required seam uploads via jnp.asarray (zero-copy "
                "alias) — this statement is contractually a COPY "
                "(jnp.array / sanitize.upload_copied)",
                context=qual))
        elif not copying:
            findings.append(Finding(
                RULE, ctx.path, lo, stmt.col_offset,
                "copy-required pragma but no copying upload "
                "(jnp.array / .copy() / np.ascontiguousarray / "
                "sanitize.upload_copied) on this statement — stale pragma "
                "or unprotected seam",
                context=qual))
    return findings
