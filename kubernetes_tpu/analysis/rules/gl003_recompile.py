"""GL003 — recompile hazards.

XLA specializes a jitted callable per (shape, dtype, static-arg) signature;
minting fresh signatures in a loop is a multi-second compile per iteration
on a tunneled backend (the arrival-stream ragged-pop storm wave_pad_floor
exists to kill: pops of 345, 589, 100 ... each compiled their own wave
shape). Two provable shapes fire:

1. `jax.jit(...)` (or `functools.partial(jax.jit, ...)`) constructed
   inside a function or loop body — every evaluation builds a NEW jitted
   callable with an empty compile cache. The blessed idiom is a
   module-level wrap (`_fused_eval_jit = jax.jit(...)`) or decorator.
2. a known-jitted callable invoked inside a for/while loop with an
   argument sliced to a DATA-DEPENDENT bound (`xs[:n]`, `xs[i:j]` with
   non-constant bounds) — each distinct length is a fresh compile. The
   blessed idiom pads to a power-of-2 bucket (`predicates.bucket`,
   `wave_pad_floor`) so the shape set is bounded at log2(max).
"""

from __future__ import annotations

import ast
from typing import List

from kubernetes_tpu.analysis.rules.base import (
    FileContext,
    Finding,
    ProjectIndex,
    _is_jit_expr,
    dotted,
    functions_of,
    last_component,
)

RULE = "GL003"


def _ragged_slice(expr: ast.AST) -> bool:
    """A subscript whose slice has a non-constant bound anywhere in expr."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Subscript) and isinstance(node.slice,
                                                          ast.Slice):
            for bound in (node.slice.lower, node.slice.upper):
                if bound is not None and not isinstance(bound, ast.Constant):
                    return True
    return False


def check(ctx: FileContext, index: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []

    # shape 1: jit construction inside a function/loop body
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and _is_jit_expr(node):
            fn = ctx.enclosing_function(node)
            if fn is not None and any(
                    node is d or node in set(ast.walk(d))
                    for d in fn.decorator_list):
                # @jax.jit / @partial(jax.jit, ...) decorator: evaluated
                # once at DEF time — blessed for top-level defs (the AST
                # parents the decorator under the decorated function). A
                # decorated def NESTED in a function still re-jits per
                # enclosing call, so only hoist one level and re-judge.
                fn = ctx.enclosing_function(fn)
                if fn is None:
                    continue
            in_loop = any(isinstance(a, (ast.For, ast.While))
                          for a in ctx.ancestors(node))
            if fn is None and not in_loop:
                continue  # module-level wrap: the blessed idiom
            where = "a loop body" if in_loop else \
                f"function {ctx.qualname(fn)}"
            findings.append(Finding(
                RULE, ctx.path, node.lineno, node.col_offset,
                f"jax.jit constructed inside {where} — every evaluation "
                "mints a fresh callable with an empty compile cache; wrap "
                "once at module level (the _fused_eval_jit idiom)",
                context=ctx.qualname(fn) if fn is not None else "<module>"))

    # shape 2: jitted call with ragged slice operand inside a loop (one
    # pass over all calls; ancestor check finds the enclosing loop, so a
    # call can never be reported twice)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fname = dotted(node.func)
        if fname is None or last_component(fname) not in index.jitted_names:
            continue
        if not any(isinstance(a, (ast.For, ast.While))
                   for a in ctx.ancestors(node)):
            continue
        ragged = [a for a in list(node.args)
                  + [k.value for k in node.keywords]
                  if _ragged_slice(a)]
        if ragged:
            efn = ctx.enclosing_function(node)
            findings.append(Finding(
                RULE, ctx.path, node.lineno, node.col_offset,
                f"jitted '{last_component(fname)}' called in a "
                "loop with a data-dependent slice operand — each "
                "distinct length compiles a fresh kernel (the "
                "ragged-pop storm); pad to a shape bucket "
                "(predicates.bucket / wave_pad_floor)",
                context=ctx.qualname(efn) if efn is not None
                else "<module>"))
    return findings
