"""GL008 — blocking call on the event-loop thread.

The binary wire's whole design premise (r13) is ONE asyncio loop owning
every socket: accepts, frame parsing and response writes all run on the
loop thread, and anything that can block hops to the executor. That
invariant lived in prose ("the backend walk takes the backend lock —
off the event loop like every service touch"); this rule is the prose,
enforced. Inside `async def` bodies — excluding nested defs and
lambdas, which run on some OTHER call stack (the executor hop itself) —
four blocking shapes fire:

1. `time.sleep(...)` — the loop sleeps, every connection stalls (the
   async twin is `await asyncio.sleep`);
2. acquiring a threading lock: `with <lock>:` / `<lock>.acquire()` on a
   provable lock (class attr, local, or module lock) — under contention
   the loop parks on a host mutex while holding every socket;
3. blocking socket ops: `socket.create_connection`, `socket.getaddrinfo`
   and `.recv/.recv_into/.recvfrom/.accept/.sendall` method calls — the
   loop already owns the sockets; raw ops belong behind
   `loop.sock_*`/streams or on the executor;
4. a device->host sync on a jitted result (the GL002 registry's taint
   machinery, re-run here): fetching a device value parks the loop
   behind the accelerator queue — the one stall no executor hop hides.

Blessed hops — a provably tiny critical section the loop may take, a
deliberate startup-path block — carry `# graftlint: block-ok` naming
why the loop can afford it.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from kubernetes_tpu.analysis.rules.base import (
    SYNC_BUILTINS,
    SYNC_METHODS,
    SYNC_WRAPPERS,
    FileContext,
    Finding,
    ProjectIndex,
    class_lock_attrs,
    dotted,
    functions_of,
    local_aliases,
    lock_ctor_kind,
    module_id,
    resolve,
    walk_shallow,
)
from kubernetes_tpu.analysis.rules.gl002_hostsync import (
    _taint_events,
    _taint_of,
)

RULE = "GL008"

_SOCKET_FUNCS = frozenset({"socket.create_connection",
                           "socket.getaddrinfo", "socket.gethostbyname"})
_SOCKET_METHODS = frozenset({"recv", "recv_into", "recvfrom", "accept",
                             "sendall"})


def _lock_path(ctx: FileContext, fn: ast.AST, expr: ast.AST,
               aliases) -> Optional[str]:
    """The resolved dotted path when `expr` provably names a threading
    lock visible from `fn` (self attr / local binding / module lock)."""
    path = resolve(dotted(expr), aliases)
    if path is None:
        return None
    if path.startswith("self.") and path.count(".") == 1:
        attr = path.split(".", 1)[1]
        klass = ctx.enclosing_class(fn)
        if klass is not None and attr in class_lock_attrs(klass):
            return path
        return None
    if "." not in path:
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) \
                    and any(isinstance(t, ast.Name) and t.id == path
                            for t in node.targets) \
                    and lock_ctor_kind(node.value) is not None:
                return path
    return None


def _module_lock(ctx: FileContext, index: ProjectIndex,
                 path: Optional[str]) -> bool:
    return path is not None and "." not in path and \
        f"{module_id(ctx.path)}.{path}" in index.module_locks


def check(ctx: FileContext, index: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []

    def fire(node: ast.AST, fn: ast.AST, what: str) -> None:
        findings.append(Finding(
            RULE, ctx.path, node.lineno, node.col_offset,
            f"{what} inside `async def` {fn.name} blocks the event-loop "
            "thread — every connection this loop owns stalls with it; "
            "hop to the executor (run_in_executor), use the async twin, "
            "or bless a provably tiny block with `# graftlint: "
            "block-ok`",
            context=ctx.qualname(fn)))

    for fn in functions_of(ctx.tree):
        if not isinstance(fn, ast.AsyncFunctionDef):
            continue
        aliases = local_aliases(fn)
        events = _taint_events(fn, index.jitted_names)
        for node in walk_shallow(fn):
            if isinstance(node, ast.With):
                for item in node.items:
                    p = _lock_path(ctx, fn, item.context_expr, aliases)
                    if p is None and _module_lock(
                            ctx, index, dotted(item.context_expr)):
                        p = dotted(item.context_expr)
                    if p is not None:
                        fire(item.context_expr, fn,
                             f"acquiring threading lock {p}")
                continue
            if not isinstance(node, ast.Call):
                continue
            fname = dotted(node.func)
            if fname == "time.sleep":
                fire(node, fn, "time.sleep")
            elif fname in _SOCKET_FUNCS:
                fire(node, fn, f"blocking {fname}")
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _SOCKET_METHODS:
                fire(node, fn, f"blocking socket .{node.func.attr}()")
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "acquire":
                p = _lock_path(ctx, fn, node.func.value, aliases)
                if p is None and _module_lock(ctx, index,
                                              dotted(node.func.value)):
                    p = dotted(node.func.value)
                if p is not None:
                    fire(node, fn, f"acquiring threading lock {p}")
            else:
                forced = None
                why = None
                if fname in SYNC_WRAPPERS and node.args:
                    why = _taint_of(node.args[0], events, node.lineno)
                    forced = fname
                elif fname in SYNC_BUILTINS and len(node.args) == 1:
                    why = _taint_of(node.args[0], events, node.lineno)
                    forced = f"{fname}()"
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr in SYNC_METHODS:
                    why = _taint_of(node.func.value, events, node.lineno)
                    forced = f".{node.func.attr}()"
                if why is not None:
                    fire(node, fn,
                         f"device->host sync ({forced} on {why})")
    return findings
