"""Shared AST plumbing for the graftlint rules.

Every rule is a pure function of one parsed file (`FileContext`) plus the
cross-file `ProjectIndex` (the jit registry — which bare names are jitted
callables anywhere in the linted set). Rules return `Finding`s; pragma
suppression and baselines are applied by the engine (analysis/lint.py), so
rules stay oblivious to both.

Design bias: PRECISION over recall. The clean-tree gate runs in tier-1, so
a false positive is a broken build for every future PR; a false negative is
just a hazard the next reviewer still has to catch by eye. Rules therefore
fire only on shapes they can actually prove from the AST (exact dotted
paths, same-function ordering, class-scoped lifetimes) and leave the
undecidable rest to the runtime sanitizer (analysis/sanitize.py).
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
from typing import Dict, Iterator, List, Optional, Set, Tuple

# rule -> pragma tag that suppresses it (plus the generic disable=GLxxx)
SUPPRESS_TAGS = {
    "GL001": "alias-ok",
    "GL002": "sync-ok",
    "GL003": "recompile-ok",
    "GL004": "tracer-ok",
    "GL005": "gen-ok",
    "GL006": "lock-ok",
    "GL007": "torn-ok",
    "GL008": "block-ok",
    "GL009": "spawn-ok",
}

# WaveHandle fields documented as un-fetched DEVICE arrays: touching one
# with a sync-forcer is a pipeline stall whether or not the local dataflow
# shows the producing jit call (the handle crosses dispatch->harvest).
DEVICE_ATTRS = frozenset({"packed", "state_out", "counter_out",
                          "committed_out"})

# snapshot arrays mutated in place by the delta-refresh/assume machinery;
# a row write to one of these without a paired dirty-note/generation bump
# leaves every (vocab_gen/version)-keyed consumer reading a stale mirror
DYNAMIC_ATTRS = frozenset({
    "requested", "nonzero", "pod_count", "port_bitmap", "_raw_dyn",
    "vol_present", "vol_rw", "pd_present", "pd_counts", "labels",
    "image_sizes",
})

# ndarray methods that mutate the receiver in place
MUTATOR_METHODS = frozenset({"fill", "sort", "put", "partition", "resize",
                             "itemset", "setfield"})

SYNC_WRAPPERS = frozenset({"np.asarray", "np.array", "numpy.asarray",
                           "numpy.array", "jax.device_get"})
SYNC_METHODS = frozenset({"item", "tolist", "block_until_ready"})
SYNC_BUILTINS = frozenset({"float", "int", "bool"})

TRACE_CONSUMERS = frozenset({"while_loop", "scan", "cond", "fori_loop",
                             "switch", "vmap", "grad", "checkpoint",
                             "remat"})

# lock constructors the concurrency family (GL006-GL009) recognizes:
# the raw threading primitives AND the lockcheck factories the shipped
# tree uses (analysis/lockcheck.py — same object either way, plus the
# tsan-lite instrumentation under GRAFT_LOCKCHECK=1). kind matters:
# re-acquiring a non-reentrant "lock" on the same object is a provable
# self-deadlock; "rlock"/"condition" are reentrant.
LOCK_CTORS = {
    "threading.Lock": "lock", "Lock": "lock",
    "threading.RLock": "rlock", "RLock": "rlock",
    "threading.Condition": "condition", "Condition": "condition",
    "lockcheck.make_lock": "lock", "make_lock": "lock",
    "lockcheck.make_rlock": "rlock", "make_rlock": "rlock",
    "lockcheck.make_condition": "condition", "make_condition": "condition",
}

# container/ndarray methods that mutate the receiver in place — the
# write half of GL007's guarded-field accounting (MUTATOR_METHODS is the
# ndarray subset GL001/GL005 already key on)
MUTATING_METHODS = MUTATOR_METHODS | frozenset({
    "append", "appendleft", "extend", "insert", "pop", "popleft",
    "popitem", "remove", "clear", "update", "setdefault", "add",
    "discard", "move_to_end",
})


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    context: str = ""  # enclosing qualname — the line-drift-stable anchor

    def fingerprint(self) -> str:
        """Stable identity for baseline suppression: deliberately excludes
        the line number so unrelated edits above a known finding don't
        un-suppress it."""
        raw = f"{self.rule}|{self.path}|{self.context}|{self.message}"
        return hashlib.sha1(raw.encode()).hexdigest()[:16]

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


class ProjectIndex:
    """Cross-file facts collected in a first pass over the whole linted set."""

    def __init__(self) -> None:
        # bare names that resolve to jit-compiled callables somewhere in the
        # set: decorated defs and module-level `NAME = jax.jit(...)` binds.
        # Imports carry the same bare name, so last-component matching on
        # call sites works across modules without an import resolver.
        self.jitted_names: Set[str] = set()
        # def names handed to jax.jit at module level (the wrapped function
        # itself is a traced scope for GL004 even though callers go through
        # the wrapper name)
        self.traced_defs: Set[str] = set()
        # class name -> {attr: lock kind} for every class in the linted set
        # that binds a threading/lockcheck primitive to self.<attr> (or a
        # class-level attr). Lock IDs are "<ClassName>.<attr>" — the same
        # spelling the lockcheck factories are handed at the call sites,
        # so the static graph and the runtime checker speak one namespace.
        self.lock_classes: Dict[str, Dict[str, str]] = {}
        # module-level locks: "<module id>.<name>" -> kind
        self.module_locks: Dict[str, str] = {}
        # GL006 project-wide state, filled by gl006_lockorder.prepare():
        # observed edges (a, b) -> [(path, qualname, b-site line)] meaning
        # lock b was acquired while a was held; declared edges from
        # `# graftlint: lock-order(a,b,...)` pragmas -> declaration site.
        self.lock_edges: Dict[Tuple[str, str], List[Tuple[str, str, int]]] \
            = {}
        self.lock_decls: Dict[Tuple[str, str], str] = {}

    def scan(self, tree: ast.Module, path: Optional[str] = None) -> None:
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(_is_jit_expr(d) for d in node.decorator_list):
                    self.jitted_names.add(node.name)
                    self.traced_defs.add(node.name)
            elif isinstance(node, ast.ClassDef):
                attrs = class_lock_attrs(node)
                if attrs:
                    self.lock_classes.setdefault(node.name, {}).update(attrs)
        for stmt in tree.body:
            if isinstance(stmt, ast.Assign) and _is_jit_expr(stmt.value):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        self.jitted_names.add(t.id)
                call = stmt.value
                if isinstance(call, ast.Call):
                    for a in call.args:
                        if isinstance(a, ast.Name):
                            self.traced_defs.add(a.id)
            elif isinstance(stmt, ast.Assign):
                kind = lock_ctor_kind(stmt.value)
                if kind is not None:
                    mod = module_id(path) if path else "<module>"
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            self.module_locks[f"{mod}.{t.id}"] = kind


class FileContext:
    def __init__(self, path: str, source: str) -> None:
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.pragmas: Dict[int, Set[str]] = _parse_pragmas(source)
        self._parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self._parents.get(node)
        while cur is not None:
            yield cur
            cur = self._parents.get(cur)

    def qualname(self, node: ast.AST) -> str:
        parts: List[str] = []
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(anc.name)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            parts.insert(0, node.name)
        return ".".join(reversed(parts))

    def enclosing_function(self, node: ast.AST):
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def enclosing_class(self, node: ast.AST):
        for anc in self.ancestors(node):
            if isinstance(anc, ast.ClassDef):
                return anc
        return None

    def tags_at(self, line: int) -> Set[str]:
        return self.pragmas.get(line, set())

    def tags_for_span(self, lo: int, hi: int) -> Set[str]:
        out: Set[str] = set()
        for ln in range(lo, hi + 1):
            out |= self.pragmas.get(ln, set())
        return out

    def suppressed(self, rule: str, node: ast.AST) -> bool:
        """A finding anchored at `node` is suppressed by a matching pragma
        (a) on any physical line of the anchoring statement, (b) on the
        line directly above it, or (c) on the enclosing `def` line (or the
        line above THAT) — the function-scope form for seams whose whole
        body shares one justification."""
        want = {SUPPRESS_TAGS[rule], f"disable={rule}", "disable=all"}
        # anchor on the SMALLEST enclosing statement; for a compound
        # statement (with/if/for — and def/class, which are ast.stmt too)
        # use only its header lines, else one pragma would smear over the
        # whole body and silently bless unrelated findings inside it
        stmt = node
        if not isinstance(node, ast.stmt):
            for anc in self.ancestors(node):
                if isinstance(anc, ast.stmt):
                    stmt = anc
                    break
        lo = getattr(stmt, "lineno", node.lineno)
        hi = getattr(stmt, "end_lineno", lo) or lo
        body = getattr(stmt, "body", None)
        if isinstance(body, list) and body:
            hi = min(hi, body[0].lineno - 1)
        hi = max(hi, getattr(node, "end_lineno", lo) or lo)
        if (self.tags_for_span(lo, hi) | self.tags_at(lo - 1)) & want:
            return True
        fn = self.enclosing_function(node)
        while fn is not None:
            d = fn.lineno
            span = self.tags_at(d) | self.tags_at(d - 1)
            for dec in fn.decorator_list:
                span |= self.tags_at(dec.lineno)
            if span & want:
                return True
            fn = self.enclosing_function(fn)
        return False


_TAG_CHARS = set("abcdefghijklmnopqrstuvwxyz0123456789-=_,GL")


def _parse_pragmas(source: str) -> Dict[int, Set[str]]:
    """line (1-based) -> pragma tags. Grammar: `# graftlint: tag [tag ...]`
    followed by optional free prose (anything that stops looking like a
    tag ends the tag list — em-dashes, parens, capitalized words).

    A pragma inside a FULL-LINE comment block also applies to the first
    code line after the block, so a multi-line justification above a def
    still reaches it."""
    out: Dict[int, Set[str]] = {}
    lines = source.splitlines()
    for i, raw in enumerate(lines, start=1):
        pos = raw.find("graftlint:")
        if pos < 0 or "#" not in raw[:pos]:
            continue
        tags: Set[str] = set()
        for tok in raw[pos + len("graftlint:"):].split():
            tok = tok.strip(",;")
            if not tok or not set(tok) <= _TAG_CHARS:
                break
            for part in tok.split(","):
                if part:
                    tags.add(part)
        if tags:
            out.setdefault(i, set()).update(tags)
            if raw.lstrip().startswith("#"):
                j = i  # 0-based index of the NEXT line
                while j < len(lines) and lines[j].lstrip().startswith("#"):
                    j += 1
                if j < len(lines):
                    out.setdefault(j + 1, set()).update(tags)
    return out


# ------------------------------------------------------------- AST helpers


def dotted(node: ast.AST) -> Optional[str]:
    """`a.b.c` as a string for Name/Attribute chains, else None."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def last_component(path: str) -> str:
    return path.rsplit(".", 1)[-1]


def chain_without_root(path: str) -> str:
    """`enc.committed_nodes` -> `committed_nodes`; bare names -> ''."""
    _, _, rest = path.partition(".")
    return rest


def _is_jit_expr(node: ast.AST) -> bool:
    """jax.jit(...) / @jax.jit / functools.partial(jax.jit, ...) /
    @partial(jax.jit, ...)"""
    if isinstance(node, ast.Call):
        fn = dotted(node.func)
        if fn in ("jax.jit", "jit"):
            return True
        if fn is not None and last_component(fn) == "partial":
            return any(dotted(a) in ("jax.jit", "jit") for a in node.args)
        return False
    return dotted(node) in ("jax.jit", "jit")


def local_aliases(fn: ast.AST) -> Dict[str, str]:
    """name -> dotted path for simple `name = obj.attr[...]`-free aliases
    (`requested = self.requested`), resolved ONE level. A name rebound more
    than once is dropped — ambiguous aliases must not match anything."""
    seen: Dict[str, Optional[str]] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            path = dotted(node.value)
            if name in seen:
                seen[name] = None
            else:
                seen[name] = path if path is not None and "." in path \
                    else None
    return {k: v for k, v in seen.items() if v}


def resolve(path: Optional[str], aliases: Dict[str, str]) -> Optional[str]:
    if path is None:
        return None
    root, sep, rest = path.partition(".")
    if root in aliases:
        return aliases[root] + (sep + rest if rest else "")
    return path


def mutations_in(fn: ast.AST, aliases: Dict[str, str]
                 ) -> List[Tuple[str, int]]:
    """(dotted path, line) of every in-place buffer mutation in `fn`:
    subscript stores, augmented assigns, `np.<ufunc>.at(x, ...)`, and the
    in-place ndarray methods. Paths are alias-resolved."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AugAssign):
            targets = [node.target]
        else:
            targets = []
        for t in targets:
            if isinstance(t, ast.Subscript):
                p = resolve(dotted(t.value), aliases)
                if p:
                    out.append((p, node.lineno))
            elif isinstance(node, ast.AugAssign):
                p = resolve(dotted(t), aliases)
                if p:
                    out.append((p, node.lineno))
        if isinstance(node, ast.Call):
            fname = dotted(node.func)
            if fname and fname.endswith(".at") and node.args \
                    and fname.count(".") >= 2:
                # np.add.at(x, idx, v) / np.subtract.at / ...
                p = resolve(dotted(node.args[0]), aliases)
                if p:
                    out.append((p, node.lineno))
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in MUTATOR_METHODS:
                p = resolve(dotted(node.func.value), aliases)
                if p:
                    out.append((p, node.lineno))
    return out


def functions_of(tree: ast.Module) -> Iterator[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


# ------------------------------------------------------------ lock helpers


def lock_ctor_kind(node: ast.AST) -> Optional[str]:
    """"lock" / "rlock" / "condition" when `node` is a call to a known
    lock constructor (threading primitive or lockcheck factory), else
    None."""
    if isinstance(node, ast.Call):
        fn = dotted(node.func)
        if fn in LOCK_CTORS:
            return LOCK_CTORS[fn]
    return None


def class_lock_attrs(klass: ast.ClassDef) -> Dict[str, str]:
    """attr -> lock kind for every `self.<attr> = <lock ctor>` (or
    class-level `<attr> = <lock ctor>`) binding inside the class body."""
    attrs: Dict[str, str] = {}
    for node in ast.walk(klass):
        if not isinstance(node, ast.Assign):
            continue
        kind = lock_ctor_kind(node.value)
        if kind is None:
            continue
        for t in node.targets:
            p = dotted(t)
            if p is not None and p.startswith("self.") and p.count(".") == 1:
                attrs[p.split(".", 1)[1]] = kind
            elif isinstance(t, ast.Name):
                attrs[t.id] = kind
    return attrs


def module_id(path: str) -> str:
    """A short dotted module id for lock naming: the file path with the
    extension, path separators and any leading `kubernetes_tpu.` prefix
    folded away (`kubernetes_tpu/api/pb/__init__.py` -> `api.pb`). Files
    outside a package tree reduce to their stem (`snippet.py` ->
    `snippet`)."""
    p = path.replace("\\", "/")
    if p.endswith(".py"):
        p = p[:-3]
    parts = [c for c in p.split("/") if c not in ("", ".", "..")]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    if parts and parts[0] == "kubernetes_tpu":
        parts = parts[1:]
    return ".".join(parts[-3:]) if parts else "<module>"


def walk_shallow(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk `fn`'s body WITHOUT descending into nested function/lambda
    bodies — their statements run on some other call stack (an executor
    hop, a callback), so they must not be attributed to `fn`'s own
    execution context (GL008's whole point is WHICH thread runs a
    statement)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))
