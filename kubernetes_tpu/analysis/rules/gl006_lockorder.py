"""GL006 — lock-order cycles (deadlock potential) over the project graph.

The engine is a concurrent system end to end: the coalescer's CV, the
cache's mutex, the telemetry registry's registration lock, the ledger's
own lock — and a deadlock needs nothing more than two call paths
acquiring two of them in opposite orders. This rule builds the
project-wide acquisition graph: an edge A -> B for every `with` that
acquires lock B lexically inside a `with` holding lock A (class-scoped
identity, like GL001's lifetimes: `self._lock` in class C is the lock
"C._lock" on EVERY instance and call path). Two finding shapes:

1. cycle: an observed edge whose reverse is reachable through the graph
   (observed elsewhere, or declared) — the classic ABBA deadlock, fired
   at every observed edge on the cycle so each inversion site carries
   its own justification or fix;
2. self-deadlock: re-acquiring the SAME non-reentrant Lock expression
   inside its own `with` — blocks forever, no second thread needed.

`# graftlint: lock-order(A,B,...)` anywhere in the linted set DECLARES
the blessed order (consecutive pairs become graph edges with no site),
so a later inversion anywhere fires even before the reverse `with`
nesting is ever written — the machine-checked form of the r12 "leader
holds the CV, never the backend lock while parked" prose. Lock IDs are
"<ClassName>.<attr>" for instance locks and "<module>.<name>" for
module-level locks — the SAME names handed to the lockcheck factories,
so the static graph and the GRAFT_LOCKCHECK runtime checker speak one
namespace.

Lexical nesting within one function is the provable shape; orders built
across call boundaries (helper acquires B, caller holds A) are the
runtime checker's half. A Condition's wait() releases its lock while
parked — the lexical region still counts as held, which is conservative
in exactly the direction a deadlock analysis wants.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.analysis.rules.base import (
    FileContext,
    Finding,
    ProjectIndex,
    dotted,
    functions_of,
    local_aliases,
    lock_ctor_kind,
    module_id,
    resolve,
)

RULE = "GL006"

_DECL_RE = re.compile(r"#.*graftlint:\s*lock-order\(([^)]*)\)")


def _lock_id(ctx: FileContext, index: ProjectIndex, fn: ast.AST,
             expr: ast.AST, aliases: Dict[str, str]
             ) -> Optional[Tuple[str, str, str]]:
    """(lock id, kind, resolved expr) for a with-item context expression
    that provably names a lock; None otherwise. Resolvable shapes:
    `self.<attr>` where the enclosing class binds <attr> to a lock ctor,
    a bare/aliased name bound to a lock ctor in this function, and a
    module-level lock of this file."""
    path = resolve(dotted(expr), aliases)
    if path is None:
        return None
    if path.startswith("self.") and path.count(".") == 1:
        attr = path.split(".", 1)[1]
        klass = ctx.enclosing_class(fn)
        if klass is not None:
            kind = index.lock_classes.get(klass.name, {}).get(attr)
            if kind is not None:
                return f"{klass.name}.{attr}", kind, path
        return None
    if "." not in path:
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) \
                    and any(isinstance(t, ast.Name) and t.id == path
                            for t in node.targets):
                kind = lock_ctor_kind(node.value)
                if kind is not None:
                    qual = ctx.qualname(fn)
                    return f"{qual}.{path}", kind, path
        mid = f"{module_id(ctx.path)}.{path}"
        kind = index.module_locks.get(mid)
        if kind is not None:
            return mid, kind, path
    return None


def _collect(ctx: FileContext, index: ProjectIndex):
    """(edges, reacquires) for one file: edges maps (held id, acquired
    id) -> [(qualname, line)], reacquires lists provable same-expression
    re-acquisitions of a non-reentrant Lock."""
    edges: Dict[Tuple[str, str], List[Tuple[str, int]]] = {}
    reacquires: List[Tuple[str, int, str]] = []

    for fn in functions_of(ctx.tree):
        aliases = local_aliases(fn)
        qual = ctx.qualname(fn)

        def visit(node: ast.AST, held: List[Tuple[str, str, str]]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return  # nested defs run on their own call stack
            if isinstance(node, (ast.With, ast.AsyncWith)):
                acquired = []
                for item in node.items:
                    lid = _lock_id(ctx, index, fn, item.context_expr,
                                   aliases)
                    if lid is None:
                        continue
                    ident, kind, expr_s = lid
                    for hid, hkind, hexpr in held:
                        if hid == ident:
                            # same LOCK NAME: orderable only when it is
                            # provably the same object (same resolved
                            # expression) — then a plain Lock deadlocks
                            # against itself right here
                            if kind == "lock" and hexpr == expr_s:
                                reacquires.append((qual, node.lineno,
                                                   ident))
                            continue
                        edges.setdefault((hid, ident), []).append(
                            (qual, node.lineno))
                    acquired.append((ident, kind, expr_s))
                inner = held + acquired
                for child in node.body:
                    visit(child, inner)
                return
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for child in ast.iter_child_nodes(fn):
            visit(child, [])
    return edges, reacquires


def prepare(contexts: List[FileContext], index: ProjectIndex) -> None:
    """Pass-1.5 hook (lint.run_paths): fold every file's declarations and
    observed edges into the project-wide graph BEFORE any check() runs,
    so cycles spanning files fire at each participating site."""
    for ctx in contexts:
        for m in _DECL_RE.finditer(ctx.source):
            ids = [s.strip() for s in m.group(1).split(",") if s.strip()]
            for a, b in zip(ids, ids[1:]):
                index.lock_decls[(a, b)] = ctx.path
        edges, _re = _collect(ctx, index)
        for key, sites in edges.items():
            index.lock_edges.setdefault(key, []).extend(
                (ctx.path, q, ln) for q, ln in sites)


def _adjacency(index: ProjectIndex) -> Dict[str, List[str]]:
    adj: Dict[str, List[str]] = {}
    for a, b in list(index.lock_edges) + list(index.lock_decls):
        adj.setdefault(a, []).append(b)
    return adj


def _find_path(adj: Dict[str, List[str]], src: str, dst: str
               ) -> Optional[List[str]]:
    """A path src -> ... -> dst through the graph, or None."""
    seen = {src}
    stack = [(src, [src])]
    while stack:
        cur, path = stack.pop()
        for nxt in adj.get(cur, ()):
            if nxt == dst:
                return path + [nxt]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _provenance(index: ProjectIndex, a: str, b: str) -> str:
    sites = index.lock_edges.get((a, b))
    if sites:
        path, qual, _ln = sites[0]
        return f"observed in {qual or '<module>'} ({path})"
    decl = index.lock_decls.get((a, b))
    return f"declared lock-order ({decl})" if decl else "declared"


def check(ctx: FileContext, index: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    edges, reacquires = _collect(ctx, index)

    for qual, line, ident in reacquires:
        findings.append(Finding(
            RULE, ctx.path, line, 0,
            f"re-acquiring non-reentrant lock '{ident}' inside its own "
            "`with` — this thread deadlocks against itself; use an "
            "RLock, or split the _locked helper the outer holder calls",
            context=qual))

    adj = _adjacency(index)
    for (a, b), sites in sorted(edges.items()):
        back = _find_path(adj, b, a)
        if back is None:
            continue
        hops = " -> ".join(f"'{x}'" for x in back)
        why = "; ".join(_provenance(index, x, y)
                        for x, y in zip(back, back[1:]))
        for qual, line in sites:
            findings.append(Finding(
                RULE, ctx.path, line, 0,
                f"lock-order cycle: '{a}' is held while acquiring "
                f"'{b}', but the reverse path {hops} exists ({why}) — "
                "two threads on these paths deadlock; acquire in one "
                "blessed order (declare it with `# graftlint: "
                "lock-order(...)`) or drop one lock before the other",
                context=qual))
    return findings
