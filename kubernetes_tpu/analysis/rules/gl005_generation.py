"""GL005 — snapshot dynamic-row writes without a paired generation bump.

The tensor snapshot is a MIRROR: every consumer (device upload dirt,
encoding caches keyed on version/vocab_gen/labels_gen, the hinted refresh)
trusts that any in-place write to a dynamic array was announced — a
`self.dirty` note, a `version`/`vocab_gen`/`labels_gen` bump, or the
`apply_assume_delta` generation sync. A row write without the announcement
is the worst kind of bug: everything keeps working on the stale device
copy until a placement lands on capacity that is not there.

Fires on: subscript stores / `np.<ufunc>.at` / `.fill()` targeting an
attribute path whose final component is one of the snapshot's dynamic
arrays (DYNAMIC_ATTRS — `requested`, `nonzero`, `pod_count`,
`port_bitmap`, `_raw_dyn`, volume presence, `labels`, `image_sizes`),
alias-resolved through locals (`requested = self.requested`), in a
function that touches NEITHER `<root>.dirty` NOR a generation counter of
the same root object.

Private helpers whose CALLER owns the announcement annotate the def with
`# graftlint: gen-ok — <who bumps>`.
"""

from __future__ import annotations

import ast
from typing import List, Set

from kubernetes_tpu.analysis.rules.base import (
    DYNAMIC_ATTRS,
    FileContext,
    Finding,
    ProjectIndex,
    dotted,
    functions_of,
    last_component,
    local_aliases,
    mutations_in,
)

RULE = "GL005"

_GEN_ATTRS = ("version", "vocab_gen", "labels_gen", "dirty")


def _announced_roots(fn: ast.AST) -> Set[str]:
    """Root names whose .dirty / generation counters are touched in fn."""
    roots: Set[str] = set()
    for node in ast.walk(fn):
        p = dotted(node) if isinstance(node, ast.Attribute) else None
        if p is None:
            continue
        parts = p.split(".")
        for i, comp in enumerate(parts[1:], start=1):
            if comp in _GEN_ATTRS:
                roots.add(".".join(parts[:i]))
                break
    return roots


def _classes_with_machinery(ctx: FileContext) -> set:
    """ClassDef nodes that demonstrably carry the mirror's generation
    machinery (an assignment to `self.dirty` anywhere in their body) —
    only THEIR dynamic-attr writes are in-scope. A Pod's `labels` dict or
    a PodBatch's pod-side `nonzero` share attribute names with the
    snapshot but have no dirty/version contract to violate."""
    out = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, (ast.Assign, ast.AugAssign)):
                targets = sub.targets if isinstance(sub, ast.Assign) \
                    else [sub.target]
                if any(dotted(t) == "self.dirty" for t in targets):
                    out.add(node)
                    break
    return out


def _in_scope(path: str, fn, ctx: FileContext, machinery: set) -> bool:
    root = path.partition(".")[0]
    if root == "self":
        return ctx.enclosing_class(fn) in machinery
    return root in ("snap", "snapshot") or ".snapshot." in path \
        or path.startswith("self.snapshot.")


def check(ctx: FileContext, index: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    machinery = _classes_with_machinery(ctx)
    for fn in functions_of(ctx.tree):
        aliases = local_aliases(fn)
        muts = [(p, ln) for p, ln in mutations_in(fn, aliases)
                if "." in p and last_component(p) in DYNAMIC_ATTRS
                and _in_scope(p, fn, ctx, machinery)]
        if not muts:
            continue
        announced = _announced_roots(fn)
        for path, line in muts:
            root = path.rsplit(".", 1)[0]
            if root in announced:
                continue
            findings.append(Finding(
                RULE, ctx.path, line, 0,
                f"in-place write to dynamic snapshot row {path} with no "
                f"paired announcement ({root}.dirty note or version/"
                "vocab_gen/labels_gen bump) in this function — every "
                "generation-keyed consumer keeps reading the stale "
                "mirror (apply_assume_delta contract); announce it or "
                "mark the def `# graftlint: gen-ok` naming the caller "
                "that does",
                context=ctx.qualname(fn)))
    return findings
