"""tsan-lite runtime lock checker, armed by ``GRAFT_LOCKCHECK=1``.

The static half of the concurrency discipline (GL006-GL009) proves what
it can from the AST; this module catches the rest at runtime, the way
lockdep does in the kernel: every instrumented lock records which locks
the acquiring thread already holds, the observed (held -> acquired)
edges accumulate in one global table, and the FIRST time two locks are
taken in both orders the checker has a witness for a real deadlock
candidate — no need to actually lose the race.

Usage is the factory triple, handed the same ``"ClassName._attr"`` /
``"module.id._name"`` lock ids the static rules compute, so the static
graph and the runtime checker speak one namespace:

    self._lock = lockcheck.make_lock("SchedulerCache._lock")
    _lock = lockcheck.make_rlock("api.pb._lock")

With the knob OFF (the default, and the shipped configuration) each
factory returns the RAW ``threading`` primitive — exact pass-through,
zero wrappers, zero overhead, bit-identical scheduling. With
``GRAFT_LOCKCHECK=1`` in the environment at construction time the
factories return instrumented twins that:

- maintain a per-thread stack of held locks;
- record every (held, acquired) name edge, and report a VIOLATION when
  the reverse edge was ever observed (lock-order inversion — the GL006
  hazard, caught even when the two orders never actually race);
- RAISE on re-acquiring a non-reentrant Lock the thread already holds
  (without the checker that is not a report, it is a hang);
- support ``assert_held(lock, what)`` so ``*_locked()`` methods verify
  their caller actually holds the guard (the GL007 hazard at runtime).

Violations are RECORDED, not raised (except the guaranteed self-
deadlock): a storm test drives the real workload to completion, then
asserts ``lockcheck.violations() == []`` — one run checks both
behaviour and discipline. ``reset()`` clears state between tests.
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Dict, List, Optional, Tuple

__all__ = ["enabled", "make_lock", "make_rlock", "make_condition",
           "assert_held", "violations", "assert_clean", "reset"]


def enabled() -> bool:
    """Read the knob per call: construction sites decide instrumentation
    at lock-birth time, tests flip the env before building the world."""
    return os.environ.get("GRAFT_LOCKCHECK", "") == "1"


# ---------------------------------------------------------------- state

# the checker's own guard is a RAW lock — instrumenting it would recurse
_STATE_LOCK = threading.Lock()
# (held name, acquired name) -> site string of the first observation
_EDGES: Dict[Tuple[str, str], str] = {}
_VIOLATIONS: List[str] = []

_TLS = threading.local()


def _held_stack() -> List["_Checked"]:
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    return stack


def _call_site() -> str:
    """nearest frame outside this module — where the acquire happened."""
    here = os.path.basename(__file__)
    for fr in reversed(traceback.extract_stack(limit=12)):
        if os.path.basename(fr.filename) != here:
            return f"{fr.filename}:{fr.lineno} in {fr.name}"
    return "<unknown>"


def _record(msg: str) -> None:
    with _STATE_LOCK:
        _VIOLATIONS.append(msg)


def violations() -> List[str]:
    with _STATE_LOCK:
        return list(_VIOLATIONS)


def assert_clean() -> None:
    vs = violations()
    if vs:
        raise AssertionError(
            "lockcheck recorded %d violation(s):\n  %s"
            % (len(vs), "\n  ".join(vs)))


def reset() -> None:
    """Clear the edge table and violation log (per-thread held stacks
    drain naturally as the locks release)."""
    with _STATE_LOCK:
        _EDGES.clear()
        del _VIOLATIONS[:]


# ------------------------------------------------------------- wrappers


class _Checked:
    """Shared acquire/release bookkeeping over a raw primitive."""

    reentrant = False

    def __init__(self, name: str, raw) -> None:
        self.name = name
        self._raw = raw

    # -- bookkeeping around the raw primitive's acquire/release ---------

    def _before_acquire(self) -> bool:
        """Order + self-deadlock checks. Returns True when this is a
        reentrant re-acquire (no new held entry should be pushed)."""
        stack = _held_stack()
        for held in stack:
            if held is self:
                if self.reentrant:
                    return True
                # not a report: without the checker this thread is GONE
                raise RuntimeError(
                    f"lockcheck: thread {threading.current_thread().name} "
                    f"re-acquired non-reentrant lock {self.name} it "
                    f"already holds at {_call_site()} — guaranteed "
                    "deadlock")
        site = None
        for held in stack:
            if held.name == self.name:
                # same NAME on a different object (two instances of one
                # class): no order exists between peers, skip the edge
                continue
            edge = (held.name, self.name)
            rev = (self.name, held.name)
            with _STATE_LOCK:
                if rev in _EDGES:
                    first = _EDGES[rev]
                    if site is None:
                        site = _call_site()
                    _VIOLATIONS.append(
                        f"lock-order inversion: {self.name} acquired "
                        f"while holding {held.name} at {site}, but the "
                        f"reverse order was observed at {first}")
                elif edge not in _EDGES:
                    if site is None:
                        site = _call_site()
                    _EDGES[edge] = site
        return False

    def _push(self) -> None:
        _held_stack().append(self)

    def _pop(self) -> None:
        stack = _held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                return

    def _is_held(self) -> bool:
        return any(h is self for h in _held_stack())

    # -- the lock protocol ---------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        nested = self._before_acquire()
        got = self._raw.acquire(blocking, timeout)
        if got and not nested:
            self._push()
        return got

    def release(self) -> None:
        self._raw.release()
        self._pop()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<lockcheck {type(self).__name__} {self.name!r}>"


class _CheckedLock(_Checked):
    reentrant = False

    def __init__(self, name: str) -> None:
        super().__init__(name, threading.Lock())

    def locked(self) -> bool:
        return self._raw.locked()


class _CheckedRLock(_Checked):
    reentrant = True

    def __init__(self, name: str) -> None:
        super().__init__(name, threading.RLock())
        self._depth = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        nested = self._before_acquire()
        got = self._raw.acquire(blocking, timeout)
        if got:
            self._depth += 1
            if not nested:
                self._push()
        return got

    def release(self) -> None:
        self._depth -= 1
        last = self._depth == 0
        self._raw.release()
        if last:
            self._pop()


class _CheckedCondition(_Checked):
    """Condition over its own (checked) lock. ``wait`` releases the lock
    for the duration, so the held entry pops for the sleep and comes
    back on wake — a waiter does NOT hold the lock against order checks
    run by the threads it is waiting on."""

    reentrant = False

    def __init__(self, name: str) -> None:
        super().__init__(name, threading.Condition())

    def wait(self, timeout: Optional[float] = None) -> bool:
        self._pop()
        try:
            return self._raw.wait(timeout)
        finally:
            self._push()

    def wait_for(self, predicate, timeout: Optional[float] = None):
        self._pop()
        try:
            return self._raw.wait_for(predicate, timeout)
        finally:
            self._push()

    def notify(self, n: int = 1) -> None:
        self._raw.notify(n)

    def notify_all(self) -> None:
        self._raw.notify_all()


# ------------------------------------------------------------ factories


def make_lock(name: str):
    """``threading.Lock()`` when the knob is off; the checked twin when
    ``GRAFT_LOCKCHECK=1``. ``name`` is the static lock id
    (``"ClassName._attr"`` / ``"module.id._name"``)."""
    return _CheckedLock(name) if enabled() else threading.Lock()


def make_rlock(name: str):
    return _CheckedRLock(name) if enabled() else threading.RLock()


def make_condition(name: str):
    return _CheckedCondition(name) if enabled() else threading.Condition()


def assert_held(lock, what: str = "") -> None:
    """Record a violation when the calling thread does NOT hold `lock`.
    A no-op on raw primitives (the knob-off path costs one isinstance),
    so ``*_locked()`` methods call it unconditionally."""
    if isinstance(lock, _Checked) and not lock._is_held():
        suffix = f" ({what})" if what else ""
        _record(
            f"guard not held: {lock.name} required{suffix} but thread "
            f"{threading.current_thread().name} does not hold it at "
            f"{_call_site()}")
