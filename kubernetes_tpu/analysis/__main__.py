"""CLI: `python -m kubernetes_tpu.analysis [paths ...]`.

Exit codes: 0 clean, 1 unsuppressed findings (or parse errors), 2 usage.

    python -m kubernetes_tpu.analysis kubernetes_tpu/
    python -m kubernetes_tpu.analysis --baseline graftlint_baseline.json src/
    python -m kubernetes_tpu.analysis --write-baseline graftlint_baseline.json src/
    python -m kubernetes_tpu.analysis --rules GL001,GL005 --json kubernetes_tpu/
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from kubernetes_tpu.analysis.lint import (
    RULE_IDS,
    load_baseline,
    run_paths,
    write_baseline,
)
from kubernetes_tpu.analysis.rules import CATALOG


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kubernetes_tpu.analysis",
        description="graftlint: AST hazard analysis for the JAX hot path "
                    "and the concurrency discipline (GL001 aliasing, "
                    "GL002 host-sync, GL003 recompile, GL004 tracer leak, "
                    "GL005 generation discipline, GL006 lock order, "
                    "GL007 torn read/write, GL008 event-loop blocking, "
                    "GL009 spawn safety)")
    ap.add_argument("paths", nargs="*",
                    help="files/directories to lint (default: the "
                         "kubernetes_tpu package directory)")
    ap.add_argument("--baseline", metavar="FILE",
                    help="JSON suppression file; listed fingerprints are "
                         "not reported")
    ap.add_argument("--write-baseline", metavar="FILE",
                    help="write all current findings to FILE as the new "
                         "baseline and exit 0")
    ap.add_argument("--rules", metavar="IDS",
                    help="comma-separated subset, e.g. GL001,GL005")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings on stdout")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid in RULE_IDS:
            print(f"{rid}  {CATALOG[rid]}")
        return 0

    rules = None
    if args.rules:
        rules = [r.strip().upper() for r in args.rules.split(",") if r]
        unknown = [r for r in rules if r not in RULE_IDS]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)} "
                  f"(have: {', '.join(RULE_IDS)})", file=sys.stderr)
            return 2

    paths = args.paths
    if not paths:
        import kubernetes_tpu
        paths = [os.path.dirname(os.path.abspath(kubernetes_tpu.__file__))]

    baseline = load_baseline(args.baseline) if args.baseline else None
    if args.write_baseline:
        # regenerate from the UNFILTERED findings: combining --baseline
        # with --write-baseline must not silently drop every inherited
        # suppression from the new file
        baseline = None
    findings, n_sup, errors = run_paths(paths, baseline=baseline,
                                        rules=rules)

    if args.write_baseline:
        write_baseline(args.write_baseline, findings)
        print(f"graftlint: baseline written ({len(findings)} "
              f"suppression(s)) -> {args.write_baseline}")
        for e in errors:
            # an unparseable/missing file silently SHRINKS the baseline's
            # coverage — that is a failed regeneration, same as the gate
            print(f"parse error: {e}", file=sys.stderr)
        return 1 if errors else 0

    if args.json:
        by_rule = {rid: 0 for rid in (rules or RULE_IDS)}
        for f in findings:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        print(json.dumps({
            "findings": [{"rule": f.rule, "path": f.path, "line": f.line,
                          "col": f.col, "context": f.context,
                          "message": f.message,
                          "fingerprint": f.fingerprint()}
                         for f in findings],
            "by_rule": by_rule,
            "baseline_suppressed": n_sup,
            "parse_errors": errors}, indent=2))
    else:
        for f in findings:
            print(f.render())
        for e in errors:
            print(f"parse error: {e}", file=sys.stderr)
        print(f"graftlint: {len(findings)} finding(s), {n_sup} "
              f"baseline-suppressed, {len(errors)} parse error(s)")
    return 1 if findings or errors else 0


if __name__ == "__main__":
    sys.exit(main())
