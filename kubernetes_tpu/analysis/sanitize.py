"""Runtime aliasing sanitizer for the device-upload seams.

The static rules (GL001) catch the aliasing shapes the AST can prove; this
module catches the rest AT TEST TIME. Under `GRAFT_SANITIZE=1` the upload
helpers change behavior:

- `upload_copied(host)` — seams whose contract is "the device gets its OWN
  buffer" (`_nodes_on_device`, the committed_nodes seed): after the copy,
  assert the device buffer really does NOT share memory with the host
  source. On the CPU backend `np.asarray(dev)` is a zero-copy view of the
  device buffer, so `np.shares_memory` sees straight through a
  constructor that silently degraded to an alias — the exact r07/r08
  regression, caught at the seam instead of as a placement flake.
- `upload_frozen(host)` — seams whose contract is "alias is fine because
  the host buffer is IMMUTABLE from now on" (AffinityData device bundles,
  the wave encodings' static topology views): freeze the source
  (`ndarray.flags.writeable = False`) so any later in-place write crashes
  loudly with a numpy ValueError at the WRITE site — not three waves
  later as a corrupted blind placement.
- `upload_view(host)` — seams whose contract is "alias is fine because
  the result is consumed SYNCHRONOUSLY before any host mutation"
  (predicates.node_arrays, the extender's cold path): sanitize mode
  upgrades them to verified copies, making the blessed-sync assumption
  unnecessary while the sanitizer watches.

With `GRAFT_SANITIZE` unset all three are exactly the constructors they
wrap — zero hot-path cost beyond one env check per upload (uploads are
already rare: the incremental sync moves a handful of arrays per round).
"""

from __future__ import annotations

import os

import jax.numpy as jnp
import numpy as np

__all__ = ["AliasingViolation", "enabled", "freeze", "upload_copied",
           "upload_frozen", "upload_view"]

# extra upload seams for the resident device mesh (ISSUE 12) are defined
# below: upload_copied/upload_frozen accept an optional NamedSharding so
# multi-device residency rides the SAME contract surface (and the same
# GL001 pragma discipline) as the single-device seams.


class AliasingViolation(RuntimeError):
    """A device upload that is contractually a copy aliases its host
    source — the data race GL001 exists to prevent, observed live."""


def enabled() -> bool:
    """Read the knob per call (not cached): tests toggle it via
    monkeypatch.setenv around individual drains."""
    return os.environ.get("GRAFT_SANITIZE", "") == "1"


# indirection point: the deliberately-aliasing regression test monkeypatches
# this to jnp.asarray to prove the shares-memory assert actually fires on
# the r07-style regression (tests/test_pipeline_drain.py)
_copy_ctor = jnp.array


def upload_copied(host, sharding=None):
    """Device upload with copy semantics, verified under GRAFT_SANITIZE=1.

    With `sharding` (a NamedSharding — the resident device mesh, ISSUE 12)
    the host source is copied BEFORE device_put: per-shard placement on the
    CPU backend may zero-copy an aligned slice, so the alias target must be
    a throwaway, never the live snapshot array. The copy is host-side and
    O(bytes shipped); the engine's row-delta path avoids paying it for
    untouched shards entirely (mesh.ResidentMesh.update_rows)."""
    if sharding is not None:
        import jax as _jax
        return _jax.device_put(np.array(host), sharding)
    dev = _copy_ctor(host)
    if enabled() and isinstance(host, np.ndarray):
        _assert_no_alias(dev, host)
    return dev


def upload_frozen(host, sharding=None):
    """Zero-copy device upload of a host buffer that is IMMUTABLE from this
    point on; sanitize mode seals the source so a violation crashes at the
    offending write. With `sharding`, placement goes through device_put
    onto the resident mesh — aliasing stays legal under the same frozen
    contract (per-shard views of a sealed buffer cannot race)."""
    if sharding is not None:
        import jax as _jax
        dev = _jax.device_put(host, sharding)
    else:
        dev = jnp.asarray(host)
    if enabled() and isinstance(host, np.ndarray):
        freeze(host)
    return dev


def upload_view(host):
    """Zero-copy device upload consumed synchronously by the caller (the
    result is fetched before any host mutation can run). Sanitize mode
    upgrades to a verified copy — the synchronous-consumption assumption
    then cannot be violated at all."""
    if enabled() and isinstance(host, np.ndarray):
        return upload_copied(host)
    return jnp.asarray(host)


def freeze(host: np.ndarray) -> np.ndarray:
    """Make every future in-place write to `host` raise. Reducing
    permissions is always legal, even on views; freezing a view does not
    freeze its base, so walk to the owner first when possible."""
    base = host
    while base.base is not None and isinstance(base.base, np.ndarray):
        base = base.base
    for arr in (base, host):
        try:
            arr.flags.writeable = False
        except ValueError:
            pass  # non-owning exotic view: freezing `host` itself suffices
    return host


def _assert_no_alias(dev, host: np.ndarray) -> None:
    try:
        view = np.asarray(dev)  # CPU backend: zero-copy view of the device
        # buffer; other backends may copy here, making the check vacuously
        # pass — aliasing is only possible on backends where this IS a view
    except Exception:
        return
    if np.shares_memory(view, host):
        raise AliasingViolation(
            f"device upload of {host.shape} {host.dtype} buffer aliases "
            "its host source — a copy-contract seam degraded to zero-copy "
            "(the r07 _nodes_on_device / r08 committed_nodes race class); "
            "upload with jnp.array or fix the constructor")
