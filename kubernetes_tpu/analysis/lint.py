"""graftlint engine: file discovery, two-pass analysis, pragmas, baseline.

Pure stdlib + AST — importing this module never imports jax/numpy, so the
tier-1 clean-tree gate and `bench.py --lint-gate` cost milliseconds and
run identically on a box with no accelerator.

Pass 1 builds the cross-file ProjectIndex (which bare names are jitted
callables anywhere in the set — GL002's taint sources and GL003's
call-site registry). Pass 2 runs every rule per file. Suppression layers,
in order:

1. pragmas — `# graftlint: <tag>` on the finding's statement, the line
   above it, or the enclosing `def` line (see rules/base.py tag table;
   `disable=GL00x` works for every rule). Pragmas are the PREFERRED
   suppression: the justification lives next to the code it blesses.
2. baseline — a JSON file of fingerprints (`--write-baseline`) for
   findings inherited from before the rule existed. Fingerprints hash
   (rule, path, enclosing qualname, message), not line numbers, so edits
   above a baselined finding don't un-suppress it. The shipped tree
   carries an EMPTY baseline: every finding is either fixed or pragma'd.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from kubernetes_tpu.analysis.rules import (
    ALL_RULES,
    RULE_IDS,
    FileContext,
    Finding,
    ProjectIndex,
)

__all__ = ["Finding", "run_paths", "lint_gate", "load_baseline",
           "write_baseline", "collect_files", "RULE_IDS"]

_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "node_modules",
              "build", "dist"}


def collect_files(paths: Sequence[str]) -> List[str]:
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
                files.extend(os.path.join(root, n) for n in sorted(names)
                             if n.endswith(".py"))
        elif p.endswith(".py"):
            files.append(p)
    return files


# the checkout that contains this very module — the stable anchor for
# fingerprint paths (parent of the kubernetes_tpu package dir)
_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def _relpath(path: str) -> str:
    """Repo-stable path form for fingerprints and reports. Files inside
    this checkout anchor to the REPO ROOT, so the same file fingerprints
    the same whether linted as `kubernetes_tpu/`, `./kubernetes_tpu/`, or
    the absolute package dir (lint_gate's default), and regardless of the
    CWD the linter runs from — else a baseline written one way suppresses
    nothing the other way. Out-of-tree files (fixture dirs) fall back to
    CWD-relative, else normalized as given."""
    ap = os.path.abspath(path)
    for root in (_REPO_ROOT, os.getcwd()):
        if ap == root or ap.startswith(root + os.sep):
            return os.path.relpath(ap, root)
    return os.path.normpath(path)


def run_paths(paths: Sequence[str],
              baseline: Optional[Dict[str, str]] = None,
              rules: Optional[Iterable[str]] = None,
              ) -> Tuple[List[Finding], int, List[str]]:
    """Lint every .py under `paths`. Returns (unsuppressed findings sorted
    by location, count suppressed by the baseline, parse-error notes).
    Pragma-suppressed findings are never materialized at all."""
    want = set(rules) if rules is not None else set(RULE_IDS)
    contexts: List[FileContext] = []
    errors: List[str] = []
    # validate per path: a typo'd/renamed path must FAIL the gate even when
    # OTHER paths yield files — else a CI arg list quietly stops covering a
    # since-renamed subtree while the gate keeps passing
    files: List[str] = []
    seen = set()
    for p in paths or ("<none>",):
        sub = collect_files([p])
        if not sub:
            errors.append(f"no Python files found under: {p}")
        files.extend(f for f in sub if f not in seen)
        seen.update(sub)
    index = ProjectIndex()
    for f in files:
        try:
            with open(f, "r", encoding="utf-8") as fh:
                src = fh.read()
            ctx = FileContext(_relpath(f), src)
        except (OSError, SyntaxError, ValueError) as e:
            errors.append(f"{f}: {e}")
            continue
        contexts.append(ctx)
        index.scan(ctx.tree, ctx.path)
    # pass 1.5: project-wide rule state (GL006's lock graph) — built over
    # the FULL set before any per-file check runs, so cross-file cycles
    # fire at every participating site
    for mod in ALL_RULES:
        prep = getattr(mod, "prepare", None)
        if prep is not None and mod.RULE in want:
            prep(contexts, index)

    findings: List[Finding] = []
    suppressed = 0
    base = baseline or {}
    for ctx in contexts:
        by_line = _nodes_by_line(ctx)
        for mod in ALL_RULES:
            if mod.RULE not in want:
                continue
            for fd in mod.check(ctx, index):
                # rules anchor findings on nodes; re-check pragma scope via
                # the reported line's nodes (one walk per file, not per
                # finding)
                if any(ctx.suppressed(fd.rule, n)
                       for n in by_line.get(fd.line, ())):
                    continue
                if fd.fingerprint() in base:
                    suppressed += 1
                    continue
                findings.append(fd)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, suppressed, errors


def _nodes_by_line(ctx: FileContext) -> Dict[int, list]:
    import ast
    out: Dict[int, list] = {}
    for node in ast.walk(ctx.tree):
        ln = getattr(node, "lineno", None)
        if ln is not None and isinstance(node, (ast.expr, ast.stmt)):
            out.setdefault(ln, []).append(node)
    return out


# ------------------------------------------------------------------ baseline


def load_baseline(path: str) -> Dict[str, str]:
    """fingerprint -> human note. Missing file = empty baseline (a fresh
    tree has nothing to inherit)."""
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    entries = data.get("suppressions", data) if isinstance(data, dict) \
        else {}
    return {str(k): str(v) for k, v in entries.items()}


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    entries = {f.fingerprint(): f.render() for f in findings}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"comment": "graftlint baseline — regenerate with "
                              "`python -m kubernetes_tpu.analysis "
                              "--write-baseline <file> <paths>`; prefer "
                              "pragmas for anything new",
                   "suppressions": entries}, fh, indent=2, sort_keys=True)
        fh.write("\n")


# ------------------------------------------------------------------ the gate


def lint_gate(root: Optional[str] = None,
              baseline: Optional[str] = None) -> Tuple[bool, str]:
    """(clean, report) over the package tree — the tier-1 / bench gate.
    Defaults to the installed kubernetes_tpu package directory so the gate
    checks the code actually being exercised, wherever it runs from."""
    if root is None:
        import kubernetes_tpu
        root = os.path.dirname(os.path.abspath(kubernetes_tpu.__file__))
    base = load_baseline(baseline) if baseline else None
    findings, n_sup, errors = run_paths([root], baseline=base)
    lines = [f.render() for f in findings] + \
        [f"parse error: {e}" for e in errors]
    ok = not findings and not errors
    tail = (f"graftlint: {len(findings)} finding(s), "
            f"{n_sup} baseline-suppressed")
    return ok, "\n".join(lines + [tail])
