"""graftlint: static + runtime hazard analysis for the JAX hot path.

PRs 2 and 3 each fixed a REAL data race of the identical class — the CPU
backend zero-copies aligned numpy uploads, so an in-place write to a
buffer a still-executing async wave reads corrupts placements silently
(`engine/scheduler_engine.py` `_nodes_on_device` / `committed_nodes`).
Both PRs also fought recompile storms and hidden device→host syncs by
hand. Those hazard classes are STRUCTURAL here: the whole design keeps
findNodesThatFit/PrioritizeNodes on-device as one fused async dispatch,
so host buffers alias device reads by default and every host touch of a
device value is a pipeline stall. Borg/Omega-lineage systems survive at
scale because invariants are checked by tooling, not reviewer vigilance
(PAPERS.md: Omega, Firmament) — this package is that tooling.

Two halves:

- `lint` + `rules/`: an AST rules engine over the package. Typed
  findings GL001–GL005 (aliasing upload, host-sync in hot path,
  recompile hazard, tracer leak, snapshot generation discipline), with
  `# graftlint:` pragmas for blessed sites and a JSON baseline for
  everything else. CLI: `python -m kubernetes_tpu.analysis <paths>`.
- `sanitize`: a runtime sanitizer. Under GRAFT_SANITIZE=1 the device-
  upload helpers freeze zero-copy sources (ndarray writeable=False) and
  assert copy seams really copied, so an aliasing violation crashes
  loudly at test time instead of corrupting a blind wave.

tests/test_graftlint.py pins the clean-tree gate (tier-1) and per-rule
fixtures; bench.py --lint-gate refuses to report perf numbers from a
tree with unsuppressed hazards.
"""

from kubernetes_tpu.analysis.lint import (  # noqa: F401
    Finding,
    lint_gate,
    load_baseline,
    run_paths,
    write_baseline,
)
