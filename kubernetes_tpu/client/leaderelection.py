"""Leader election over an apiserver-lite lock object.

Mirrors client-go tools/leaderelection (leaderelection.go:138 Run =
acquire -> renew loop; resourcelock/ holds the LeaderElectionRecord in an
object annotation — here a first-class Lease record, the direction upstream
later took with coordination/v1). Semantics preserved:

- acquire: create the lock if absent, else take over only when the holder's
  renew_time is older than lease_duration (leaderelection.go tryAcquireOrRenew).
- renew: CAS on resourceVersion every retry_period; losing the CAS or the
  lock means stepping down (OnStoppedLeading).
- observers watching the same object see holder identity changes.

The scheduler/controller-manager binaries run under this exactly like the
reference's --leader-elect (plugin/cmd/kube-scheduler/app/server.go:127-146).
The TPU sidecar is stateless (SURVEY.md §5.4), so failover = the new leader
re-snapshots; no device state must be handed over.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from kubernetes_tpu.server.apiserver_lite import ApiServerLite, Conflict, NotFound


@dataclass
class Lease:
    """resourcelock.LeaderElectionRecord as a stored object."""

    name: str
    namespace: str = "kube-system"
    holder: str = ""
    lease_duration: float = 15.0
    acquire_time: float = 0.0
    renew_time: float = 0.0
    leader_transitions: int = 0
    resource_version: int = 0


class LeaseLock:
    """resourcelock.Interface: Get/Create/Update of the Lease object."""

    KIND = "Lease"

    def __init__(self, api: ApiServerLite, name: str, namespace: str = "kube-system"):
        self.api = api
        self.name = name
        self.namespace = namespace

    def get(self) -> Lease:
        return self.api.get(self.KIND, self.namespace, self.name)

    def create(self, lease: Lease) -> int:
        return self.api.create(self.KIND, lease)

    def update(self, lease: Lease, expect_rv: int) -> int:
        return self.api.update(self.KIND, lease, expect_rv=expect_rv)


class LeaderElector:
    """leaderelection.LeaderElector — acquire then renew until stopped or
    deposed. Defaults match LeaderElectionDefaulting: 15s lease, 10s renew
    deadline, 2s retry (pkg/client/leaderelectionconfig + apiserver defaults).
    """

    def __init__(self, lock: LeaseLock, identity: str,
                 lease_duration: float = 15.0,
                 renew_deadline: float = 10.0,
                 retry_period: float = 2.0,
                 on_started_leading: Optional[Callable[[], None]] = None,
                 on_stopped_leading: Optional[Callable[[], None]] = None,
                 now: Callable[[], float] = time.monotonic):
        self.lock = lock
        self.identity = identity
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self.on_started_leading = on_started_leading or (lambda: None)
        self.on_stopped_leading = on_stopped_leading or (lambda: None)
        self._now = now
        self._leading = False
        self._last_renew = 0.0  # last successful acquire/renew
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---------------------------------------------------------- primitives

    def is_leader(self) -> bool:
        return self._leading

    def try_acquire_or_renew(self) -> bool:
        """One tryAcquireOrRenew pass. Returns True when we hold the lock."""
        now = self._now()
        try:
            cur = self.lock.get()
        except NotFound:
            lease = Lease(name=self.lock.name, namespace=self.lock.namespace,
                          holder=self.identity, lease_duration=self.lease_duration,
                          acquire_time=now, renew_time=now)
            try:
                self.lock.create(lease)
            except Conflict:
                return False
            return True

        if cur.holder != self.identity:
            # an empty holder is a gracefully released lease (release());
            # only a live NAMED holder blocks acquisition
            if cur.holder and now < cur.renew_time + cur.lease_duration:
                return False  # current leader is live
            # lease expired: steal, bumping transitions
            lease = Lease(name=cur.name, namespace=cur.namespace,
                          holder=self.identity, lease_duration=self.lease_duration,
                          acquire_time=now, renew_time=now,
                          leader_transitions=cur.leader_transitions + 1)
        else:
            lease = Lease(name=cur.name, namespace=cur.namespace,
                          holder=self.identity, lease_duration=self.lease_duration,
                          acquire_time=cur.acquire_time, renew_time=now,
                          leader_transitions=cur.leader_transitions)
        try:
            self.lock.update(lease, expect_rv=cur.resource_version)
        except (Conflict, NotFound):
            return False
        return True

    def step(self) -> bool:
        """One election tick; fires callbacks on transitions. Usable directly
        in deterministic tests.

        A leader tolerates transient renew failures (CAS races) until
        renew_deadline elapses since the last successful renew — client-go's
        RenewDeadline window — EXCEPT when the lock shows another holder,
        which means we were actively deposed and must step down now."""
        held = self.try_acquire_or_renew()
        now = self._now()
        if held:
            self._last_renew = now
            if not self._leading:
                self._leading = True
                self.on_started_leading()
        elif self._leading:
            deposed = False
            try:
                deposed = self.lock.get().holder != self.identity
            except NotFound:
                pass  # lock vanished: treat as transient
            if deposed or now >= self._last_renew + self.renew_deadline:
                self._leading = False
                self.on_stopped_leading()
        return held

    # ------------------------------------------------------------- daemon

    def run(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"leaderelect-{self.identity}")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.step()
            self._stop.wait(self.retry_period)
        if self._leading:
            self._leading = False
            self.on_stopped_leading()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def release(self) -> bool:
        """Graceful step-down: zero out the lease's renew_time via CAS so a
        standby can acquire immediately instead of waiting out
        lease_duration (client-go's later ReleaseOnCancel behavior; 1.7
        holders just crashed and made standbys wait). Returns True if the
        lease was released. Fires on_stopped_leading."""
        was_leading = self._leading
        self._leading = False
        released = False
        try:
            cur = self.lock.get()
            if cur.holder == self.identity:
                self.lock.update(
                    Lease(name=cur.name, namespace=cur.namespace,
                          holder="", lease_duration=cur.lease_duration,
                          acquire_time=0.0, renew_time=0.0,
                          leader_transitions=cur.leader_transitions),
                    expect_rv=cur.resource_version)
                released = True
        except (Conflict, NotFound):
            pass
        if was_leading:
            self.on_stopped_leading()
        return released
