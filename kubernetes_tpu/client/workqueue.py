"""Rate-limited work queues — client-go util/workqueue semantics.

Reference behavior being mirrored (staging/src/k8s.io/client-go/util/workqueue):
- queue.go: the dirty/processing two-set invariant — an item added while being
  processed is re-queued exactly once when Done() is called; duplicate Adds
  between Get()s collapse.
- delaying_queue.go: AddAfter via a time-ordered heap drained by the consumer.
- default_rate_limiters.go: ItemExponentialFailureRateLimiter
  (base * 2^failures, capped), Forget() resets the failure count.
- parallelizer.go:29 Parallelize(workers, pieces, fn) — the scheduler's
  host-side fan-out primitive. Here it exists for host-side controller work
  only; the pod x node hot loop it powered in the reference is replaced by
  the fused device kernel (ops/predicates.py, ops/priorities.py).
"""

from __future__ import annotations

import heapq
import threading
from kubernetes_tpu.analysis import lockcheck
import time
from typing import Any, Callable, Hashable, List, Optional


class ShutDown(Exception):
    """Raised by Get() after shut_down() drains."""


class WorkQueue:
    """Deduplicating FIFO with in-flight tracking (workqueue/queue.go)."""

    def __init__(self, now: Callable[[], float] = time.monotonic):
        self._lock = lockcheck.make_condition("WorkQueue._lock")
        self._queue: List[Hashable] = []
        self._dirty: set = set()
        self._processing: set = set()
        self._shutting_down = False
        self._now = now

    def add(self, item: Hashable) -> None:
        with self._lock:
            if self._shutting_down or item in self._dirty:
                return
            self._dirty.add(item)
            if item in self._processing:
                return  # will re-queue on Done()
            self._queue.append(item)
            self._lock.notify()

    def get(self, timeout: Optional[float] = None) -> Hashable:
        """Blocks until an item is available; raises ShutDown when the queue
        is shutting down and empty, TimeoutError on timeout."""
        deadline = None if timeout is None else self._now() + timeout
        with self._lock:
            while not self._queue:
                if self._shutting_down:
                    raise ShutDown()
                remaining = None if deadline is None else deadline - self._now()
                if remaining is not None and remaining <= 0:
                    raise TimeoutError()
                self._lock.wait(remaining)
            item = self._queue.pop(0)
            self._processing.add(item)
            self._dirty.discard(item)
            return item

    def done(self, item: Hashable) -> None:
        with self._lock:
            self._processing.discard(item)
            if item in self._dirty:
                self._queue.append(item)
                self._lock.notify()

    def shut_down(self) -> None:
        with self._lock:
            self._shutting_down = True
            self._lock.notify_all()

    def __len__(self) -> int:
        with self._lock:
            return len(self._queue)


class ItemExponentialFailureRateLimiter:
    """base * 2^failures per item, capped (default_rate_limiters.go:67-102).
    Reference defaults for controllers: 5ms base, 1000s cap; the scheduler's
    pod backoff uses 1s..60s (plugin/pkg/scheduler/util/backoff_utils.go)."""

    def __init__(self, base: float = 0.005, max_delay: float = 1000.0):
        self.base = base
        self.max_delay = max_delay
        self._failures: dict = {}
        self._lock = lockcheck.make_lock("ItemExponentialFailureRateLimiter._lock")

    def when(self, item: Hashable) -> float:
        with self._lock:
            n = self._failures.get(item, 0)
            self._failures[item] = n + 1
            return min(self.base * (2 ** n), self.max_delay)

    def forget(self, item: Hashable) -> None:
        with self._lock:
            self._failures.pop(item, None)

    def retries(self, item: Hashable) -> int:
        with self._lock:
            return self._failures.get(item, 0)


class RateLimitingQueue(WorkQueue):
    """WorkQueue + AddAfter heap + per-item rate limiter
    (delaying_queue.go + rate_limiting_queue.go). Delayed items become
    visible to Get() once their ready-time passes; Get() wakes itself no
    later than the earliest pending deadline."""

    def __init__(self, rate_limiter: Optional[ItemExponentialFailureRateLimiter] = None,
                 now: Callable[[], float] = time.monotonic):
        super().__init__(now=now)
        self.rate_limiter = rate_limiter or ItemExponentialFailureRateLimiter()
        self._waiting: List[tuple] = []  # (ready_time, seq, item) heap
        self._seq = 0

    def add_after(self, item: Hashable, delay: float) -> None:
        if delay <= 0:
            self.add(item)
            return
        with self._lock:
            if self._shutting_down:
                return
            self._seq += 1
            heapq.heappush(self._waiting, (self._now() + delay, self._seq, item))
            self._lock.notify()

    def add_rate_limited(self, item: Hashable) -> None:
        self.add_after(item, self.rate_limiter.when(item))

    def forget(self, item: Hashable) -> None:
        self.rate_limiter.forget(item)

    def num_requeues(self, item: Hashable) -> int:
        return self.rate_limiter.retries(item)

    def get(self, timeout: Optional[float] = None) -> Hashable:
        deadline = None if timeout is None else self._now() + timeout
        with self._lock:
            while True:
                now = self._now()
                while self._waiting and self._waiting[0][0] <= now:
                    _, _, item = heapq.heappop(self._waiting)
                    if item not in self._dirty:
                        self._dirty.add(item)
                        if item not in self._processing:
                            self._queue.append(item)
                if self._queue:
                    item = self._queue.pop(0)
                    self._processing.add(item)
                    self._dirty.discard(item)
                    return item
                if self._shutting_down:
                    raise ShutDown()
                waits = []
                if deadline is not None:
                    waits.append(deadline - now)
                if self._waiting:
                    waits.append(self._waiting[0][0] - now)
                wait_for = min(waits) if waits else None
                if wait_for is not None and wait_for <= 0:
                    if deadline is not None and now >= deadline:
                        raise TimeoutError()
                    continue
                self._lock.wait(wait_for)
                if deadline is not None and self._now() >= deadline and not self._queue:
                    now2 = self._now()
                    pending_ready = self._waiting and self._waiting[0][0] <= now2
                    if not pending_ready:
                        raise TimeoutError()


def parallelize(workers: int, pieces: int, do_work: Callable[[int], Any]) -> None:
    """workqueue.Parallelize (parallelizer.go:29): run do_work(0..pieces-1)
    across `workers` threads, joining before return."""
    if pieces <= 0:
        return
    workers = max(1, min(workers, pieces))
    if workers == 1:
        for i in range(pieces):
            do_work(i)
        return
    counter = iter(range(pieces))
    lock = lockcheck.make_lock("parallelize.lock")
    errors: List[BaseException] = []

    def run():
        while True:
            with lock:
                i = next(counter, None)
            if i is None:
                return
            try:
                do_work(i)
            except BaseException as e:  # surface first error after join
                with lock:
                    errors.append(e)
                return

    threads = [threading.Thread(target=run, daemon=True) for _ in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]
