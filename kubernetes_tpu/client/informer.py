"""Informers: Reflector -> Store -> SharedInformer over apiserver-lite.

Mirrors client-go tools/cache (reflector.go ListAndWatch, store.go,
shared_informer.go, thread_safe_store.go indexers):

- Reflector: List() for a consistent snapshot + resourceVersion, then a watch
  loop from that rv; TooOldResourceVersion (the etcd-compaction analog)
  triggers a full relist, exactly like reflector.go's "watch of X closed with:
  too old resource version" path.
- Store: thread-safe keyed store with named indexes (thread_safe_store.go) —
  e.g. pods-by-node for the node lifecycle controller.
- SharedInformer: one reflector fanned out to N event handlers; handlers get
  (add, update(old,new), delete) callbacks and a has_synced() barrier.
- SharedInformerFactory: one informer per kind shared by all controllers, the
  informers.SharedInformerFactory analog used by the controller manager
  (cmd/kube-controller-manager/app/controllermanager.go shared informers).

Deliberate TPU-era design departure: the reference pushes every event through
DeltaFIFO goroutines; here handlers run synchronously on the informer thread
(controllers only enqueue keys, so handler work is O(µs)) and heavy state
lives in tensors refreshed from the Store's generation counters.
"""

from __future__ import annotations

import threading
from kubernetes_tpu.analysis import lockcheck
import time
from typing import Any, Callable, Dict, Hashable, List, Optional, Tuple

from kubernetes_tpu.server.apiserver_lite import (
    ApiServerLite,
    TooOldResourceVersion,
)


def meta_namespace_key(obj: Any) -> str:
    """cache.MetaNamespaceKeyFunc: "<ns>/<name>" (or "<name>" cluster-scoped)."""
    ns = getattr(obj, "namespace", "")
    return f"{ns}/{obj.name}" if ns else obj.name


class Store:
    """Thread-safe keyed object store with named indexes
    (client-go tools/cache/thread_safe_store.go)."""

    def __init__(self, key_func: Callable[[Any], str] = meta_namespace_key):
        self._key = key_func
        self._lock = lockcheck.make_rlock("Store._lock")
        self._items: Dict[str, Any] = {}
        # index name -> (index_func, value -> set of keys)
        self._indexers: Dict[str, Callable[[Any], List[str]]] = {}
        self._indices: Dict[str, Dict[str, set]] = {}

    def add_index(self, name: str, index_func: Callable[[Any], List[str]]) -> None:
        with self._lock:
            self._indexers[name] = index_func
            idx: Dict[str, set] = {}
            for key, obj in self._items.items():
                for v in index_func(obj):
                    idx.setdefault(v, set()).add(key)
            self._indices[name] = idx

    def _update_index_locked(self, key: str, old: Any, new: Any) -> None:
        lockcheck.assert_held(self._lock, "_update_index_locked")
        for name, fn in self._indexers.items():
            idx = self._indices[name]
            old_vals = set(fn(old)) if old is not None else set()
            new_vals = set(fn(new)) if new is not None else set()
            for v in old_vals - new_vals:
                bucket = idx.get(v)
                if bucket is not None:
                    bucket.discard(key)
                    if not bucket:
                        del idx[v]
            for v in new_vals - old_vals:
                idx.setdefault(v, set()).add(key)

    def upsert(self, obj: Any) -> Optional[Any]:
        """Insert/replace; returns the previous object (None if new)."""
        key = self._key(obj)
        with self._lock:
            old = self._items.get(key)
            self._items[key] = obj
            self._update_index_locked(key, old, obj)
            return old

    def remove(self, obj: Any) -> Optional[Any]:
        key = self._key(obj)
        with self._lock:
            old = self._items.pop(key, None)
            if old is not None:
                self._update_index_locked(key, old, None)
            return old

    def replace(self, objs: List[Any]) -> Tuple[List[Any], List[Any], List[Tuple[Any, Any]]]:
        """Atomic resync (store.Replace): returns (added, deleted, updated
        (old,new) pairs) relative to previous contents."""
        with self._lock:
            new_items = {self._key(o): o for o in objs}
            added = [o for k, o in new_items.items() if k not in self._items]
            deleted = [o for k, o in self._items.items() if k not in new_items]
            updated = [(self._items[k], o) for k, o in new_items.items()
                       if k in self._items and self._items[k] is not o]
            for o in deleted:
                self.remove(o)
            for o in objs:
                self.upsert(o)
            return added, deleted, updated

    def get(self, key: str) -> Optional[Any]:
        with self._lock:
            return self._items.get(key)

    def list(self) -> List[Any]:
        with self._lock:
            return list(self._items.values())

    def keys(self) -> List[str]:
        with self._lock:
            return list(self._items.keys())

    def by_index(self, name: str, value: str) -> List[Any]:
        """Indexer.ByIndex: all objects whose index_func yields `value`."""
        with self._lock:
            keys = self._indices.get(name, {}).get(value, ())
            return [self._items[k] for k in keys]

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)


class _Handler:
    __slots__ = ("on_add", "on_update", "on_delete")

    def __init__(self, on_add, on_update, on_delete):
        self.on_add = on_add or (lambda obj: None)
        self.on_update = on_update or (lambda old, new: None)
        self.on_delete = on_delete or (lambda obj: None)


class SharedInformer:
    """One kind's reflector + store + handler fan-out."""

    def __init__(self, api: ApiServerLite, kind: str,
                 key_func: Callable[[Any], str] = meta_namespace_key):
        self.api = api
        self.kind = kind
        self.store = Store(key_func)
        self._handlers: List[_Handler] = []
        self._synced = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._rv = 0
        self._lock = lockcheck.make_lock("SharedInformer._lock")

    def add_event_handler(self, on_add=None, on_update=None, on_delete=None) -> None:
        """Late handlers get synthetic ADDs for current contents, like
        sharedIndexInformer.AddEventHandler's initial delivery."""
        h = _Handler(on_add, on_update, on_delete)
        with self._lock:
            self._handlers.append(h)
            if self._synced.is_set():
                for obj in self.store.list():
                    h.on_add(obj)

    def has_synced(self) -> bool:
        return self._synced.is_set()

    # ------------------------------------------------------------ run loop

    def _relist(self) -> None:
        objs, rv = self.api.list(self.kind)
        added, deleted, updated = self.store.replace(objs)
        self._rv = rv
        with self._lock:
            handlers = list(self._handlers)
        for obj in added:
            for h in handlers:
                h.on_add(obj)
        for old, new in updated:
            for h in handlers:
                h.on_update(old, new)
        for obj in deleted:
            for h in handlers:
                h.on_delete(obj)

    def step(self, wait: float = 0.0) -> int:
        """One poll of the watch stream; usable directly in deterministic
        tests (no thread). Returns events processed."""
        if not self._synced.is_set():
            self._relist()
            self._synced.set()
            return 0
        try:
            events = self.api.watch_since((self.kind,), self._rv, timeout=wait)
        except TooOldResourceVersion:
            self._relist()
            return 0
        with self._lock:
            handlers = list(self._handlers)
        for ev in events:
            self._rv = ev.rv
            if ev.type == "DELETED":
                self.store.remove(ev.obj)
                for h in handlers:
                    h.on_delete(ev.obj)
            else:
                old = self.store.upsert(ev.obj)
                if old is None:
                    for h in handlers:
                        h.on_add(ev.obj)
                else:
                    for h in handlers:
                        h.on_update(old, ev.obj)
        return len(events)

    def run(self, poll: float = 0.05) -> None:
        self._thread = threading.Thread(
            target=self._loop, args=(poll,), daemon=True,
            name=f"informer-{self.kind}")
        self._thread.start()

    def _loop(self, poll: float) -> None:
        while not self._stop.is_set():
            self.step(wait=poll)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)


class SharedInformerFactory:
    """informers.SharedInformerFactory: one shared informer per kind."""

    def __init__(self, api: ApiServerLite):
        self.api = api
        self._informers: Dict[str, SharedInformer] = {}
        self._lock = lockcheck.make_lock("SharedInformerFactory._lock")
        self._started = False
        self._poll = 0.05

    def informer(self, kind: str) -> SharedInformer:
        with self._lock:
            inf = self._informers.get(kind)
            if inf is None:
                inf = SharedInformer(self.api, kind)
                self._informers[kind] = inf
                if self._started:
                    inf.run(self._poll)
            return inf

    def start(self, poll: float = 0.05) -> None:
        with self._lock:
            self._started = True
            self._poll = poll
            for inf in self._informers.values():
                if inf._thread is None:
                    inf.run(poll)

    def step_all(self, wait: float = 0.0) -> int:
        """Deterministic single-threaded pump for tests/benchmarks."""
        with self._lock:
            infs = list(self._informers.values())
        return sum(inf.step(wait=wait) for inf in infs)

    def wait_for_cache_sync(self, timeout: float = 10.0) -> bool:
        with self._lock:
            infs = list(self._informers.values())
        end = time.monotonic() + timeout
        for inf in infs:
            while not inf.has_synced():
                if inf._thread is None:
                    inf.step()  # no thread: pump synchronously
                    continue
                remaining = end - time.monotonic()
                if remaining <= 0:
                    return False
                inf._synced.wait(min(remaining, 0.25))
        return True

    def stop(self) -> None:
        with self._lock:
            infs = list(self._informers.values())
        for inf in infs:
            inf.stop()
