"""Client layer: the client-go analog (SURVEY.md §1 L3).

Informers (Reflector -> Store -> SharedInformer), listers, rate-limited
workqueues, leader election, and the event recorder — the substrate every
controller (L4) and agent (L6/L7) in this framework watches state through
and writes back with.
"""

from kubernetes_tpu.client.informer import SharedInformer, SharedInformerFactory, Store
from kubernetes_tpu.client.leaderelection import LeaderElector, LeaseLock
from kubernetes_tpu.client.record import EventRecorder
from kubernetes_tpu.client.workqueue import (
    ItemExponentialFailureRateLimiter,
    RateLimitingQueue,
    WorkQueue,
    parallelize,
)

__all__ = [
    "SharedInformer",
    "SharedInformerFactory",
    "Store",
    "LeaderElector",
    "LeaseLock",
    "EventRecorder",
    "WorkQueue",
    "RateLimitingQueue",
    "ItemExponentialFailureRateLimiter",
    "parallelize",
]
