"""Event recorder — client-go tools/record analog.

EventRecorder writes ClusterEvent objects through the apiserver so every
component's events are observable cluster state (the reference's
EventBroadcaster -> events API path; scheduler emits Scheduled /
FailedScheduling at plugin/pkg/scheduler/scheduler.go:174,248).

Correlation/dedup: repeated (object, reason, message) triples bump a count on
the stored event instead of creating new objects — the EventCorrelator /
EventAggregator behavior (client-go/tools/record/events_cache.go) that keeps
event storms from flooding storage.
"""

from __future__ import annotations

import dataclasses
import threading
from kubernetes_tpu.analysis import lockcheck
import time
from dataclasses import dataclass
from typing import Callable, Dict, Tuple

from kubernetes_tpu.server.apiserver_lite import ApiServerLite, Conflict, NotFound


@dataclass
class ClusterEvent:
    """v1.Event reduced to the consumed fields."""

    name: str
    namespace: str
    involved_kind: str
    involved_key: str  # "<ns>/<name>" of the object the event is about
    reason: str
    message: str
    type: str = "Normal"  # Normal | Warning
    count: int = 1
    source: str = ""
    first_seen: float = 0.0
    last_seen: float = 0.0
    resource_version: int = 0


class EventRecorder:
    KIND = "Event"

    def __init__(self, api: ApiServerLite, source: str,
                 now: Callable[[], float] = time.time):
        self.api = api
        self.source = source
        self._now = now
        self._lock = lockcheck.make_lock("EventRecorder._lock")
        self._seq = 0
        # (involved_key, reason, message) -> stored event name, for dedup
        self._names: Dict[Tuple[str, str, str], str] = {}

    def event(self, involved_kind: str, involved_key: str, event_type: str,
              reason: str, message: str) -> None:
        ts = self._now()
        dedup_key = (involved_key, reason, message)
        namespace = involved_key.split("/", 1)[0] if "/" in involved_key else "default"
        # Reserve the dedup slot atomically so concurrent first emissions of
        # the same triple agree on one stored object.
        with self._lock:
            name = self._names.get(dedup_key)
            fresh = name is None
            if fresh:
                self._seq += 1
                name = f"{involved_key.replace('/', '.')}.{reason}.{self._seq}"
                self._names[dedup_key] = name
        if not fresh:
            for _ in range(3):  # CAS retry under concurrent bumps
                try:
                    cur: ClusterEvent = self.api.get(self.KIND, namespace, name)
                    bumped = dataclasses.replace(
                        cur, count=cur.count + 1, last_seen=ts)
                    self.api.update(self.KIND, bumped,
                                    expect_rv=cur.resource_version)
                    return
                except Conflict:
                    continue
                except NotFound:
                    break  # stored event was pruned; recreate below
        ev = ClusterEvent(
            name=name, namespace=namespace, involved_kind=involved_kind,
            involved_key=involved_key, reason=reason, message=message,
            type=event_type, source=self.source, first_seen=ts, last_seen=ts)
        try:
            self.api.create(self.KIND, ev)
        except Conflict:
            # lost the create race to a concurrent emitter of the same triple;
            # their object carries the count
            pass
