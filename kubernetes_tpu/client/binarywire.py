"""Blocking binary fleet client (ISSUE 11): one frontend's connection to
the async binary wire (server/asyncwire.py), speaking server/framing.py.

One client is one scheduler's serial scheduleOne loop — request/response
on a persistent connection, like the keep-alive HTTP clients it
replaces. Typed outcomes mirror the service core's contract:

  - ``filter_fused`` returns a FilterVerdict (top scores of the same
    coalesced verdict — a fleet scheduleOne is TWO round trips);
  - ``bind`` returns a BindResult (ok/conflict/pending/shed/error with
    the server's jittered retry-after);
  - an OVERLOADED frame raises the typed ``WireOverloaded`` carrying
    retry_after_s — the caller throttles THIS step and retries, exactly
    the 429 discipline;
  - a DEADLINE frame raises ``WireDeadline`` (nothing was evaluated).

Reconnect-and-replay is the CALLER's move (bench drivers do it on socket
errors): filter is an idempotent read and bind carries its ledger key,
so a re-send of the same body is exactly the replay path the service
exists to absorb.
"""

from __future__ import annotations

import socket
from typing import List, Optional, Tuple

from kubernetes_tpu.server import framing
from kubernetes_tpu.server.embedded import BindResult, FilterVerdict


class WireOverloaded(Exception):
    """Typed OVERLOADED frame: retry this step after retry_after_s."""

    def __init__(self, retry_after_s: float):
        super().__init__(f"server overloaded; retry after "
                         f"{retry_after_s * 1e3:.0f}ms")
        self.retry_after_s = retry_after_s


class WireDeadline(Exception):
    """Typed DEADLINE frame: the request outlived its own deadline."""


class WireError(Exception):
    """Typed ERROR frame or protocol violation."""


class BinaryWireClient:
    """One serial connection to an AsyncBinaryServer."""

    def __init__(self, host: str, port: int, timeout: float = 60.0,
                 max_frame: int = framing.MAX_FRAME):
        self.host = host
        self.port = port
        self.timeout = timeout
        self.max_frame = max_frame
        self._sock: Optional[socket.socket] = None
        self._dec = framing.FrameDecoder(max_frame)
        self._req_id = 0

    # ------------------------------------------------------------ plumbing

    def connect(self) -> "BinaryWireClient":
        self.close()
        s = socket.create_connection((self.host, self.port),
                                     timeout=self.timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = s
        self._dec = framing.FrameDecoder(self.max_frame)
        return self

    def close(self) -> None:
        if self._sock is not None:
            try:
                # shutdown() before close() delivers EOF to the server's
                # reader NOW: without it, a worker process exiting with
                # a live connection leaves the server's per-connection
                # reader task parked in read() until teardown cancels it
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _roundtrip(self, verb: int, payload: bytes = b"",
                   flags: int = 0) -> Tuple[int, bytes]:
        if self._sock is None:
            self.connect()
        assert self._sock is not None
        self._req_id = (self._req_id + 1) & 0xFFFFFFFF
        req_id = self._req_id
        self._sock.sendall(framing.encode_frame(verb, req_id, payload,
                                                flags))
        while True:
            frames = self._dec.feed(self._recv())
            for rverb, _rflags, rid, rpayload in frames:
                if rid != req_id:
                    if rverb == framing.ERROR:
                        # stream-level fault: the server could not
                        # attribute a request id (corrupt length prefix,
                        # oversized frame) and answers with id 0 before
                        # closing — surface ITS message, not a bogus
                        # id-mismatch diagnosis
                        raise WireError(framing.decode_error(rpayload))
                    # a serial client never has two in flight: a stray id
                    # is a protocol violation, not something to skip past
                    raise WireError(f"response id {rid} != request "
                                    f"{req_id}")
                return self._typed(rverb, rpayload)

    def _recv(self) -> bytes:
        assert self._sock is not None
        data = self._sock.recv(65536)
        if not data:
            raise ConnectionError("server closed connection")
        return data

    @staticmethod
    def _typed(verb: int, payload: bytes) -> Tuple[int, bytes]:
        if verb == framing.OVERLOADED:
            raise WireOverloaded(framing.decode_overloaded(payload) / 1e3)
        if verb == framing.DEADLINE:
            raise WireDeadline("request shed past its deadline")
        if verb == framing.ERROR:
            raise WireError(framing.decode_error(payload))
        return verb, payload

    # --------------------------------------------------------------- verbs

    def ping(self) -> None:
        verb, _ = self._roundtrip(framing.PING)
        if verb != framing.PONG:
            raise WireError(f"unexpected verb 0x{verb:02x} to PING")

    def filter_fused(self, pod, top_k: int = 32, deadline_ms: int = 0,
                     compact: bool = True,
                     pod_blob: Optional[bytes] = None,
                     trace_ctx: Optional[str] = None) -> FilterVerdict:
        body = framing.encode_filter_request(pod, top_k=top_k,
                                             deadline_ms=deadline_ms,
                                             pod_blob=pod_blob)
        flags = framing.FLAG_COMPACT if compact else 0
        if trace_ctx:
            # pod-trace context (ISSUE 15): this hop joins the pod's
            # timeline server-side
            body = framing.wrap_trace(body, trace_ctx)
            flags |= framing.FLAG_TRACE
        verb, payload = self._roundtrip(framing.FILTER, body, flags=flags)
        if verb != framing.VERDICT:
            raise WireError(f"unexpected verb 0x{verb:02x} to FILTER")
        d = framing.decode_verdict(payload)
        return FilterVerdict(
            snapshot_gen=d["gen"], all_passed=d["all_passed"],
            passed_count=d["passed_count"],
            passed=None if (compact and d["all_passed"]) else d["passed"],
            failed={nm: "failed TPU predicate kernel"
                    for nm in d["failed"]},
            top_scores=d["top"])

    def bind(self, pod_name: str, namespace: str, uid: str, node: str,
             snapshot_gen: Optional[int] = None, idem_key: str = "",
             deadline_ms: int = 0, pod=None,
             pod_blob: Optional[bytes] = None,
             trace_ctx: Optional[str] = None) -> BindResult:
        body = framing.encode_bind_request(
            pod_name, namespace, uid, node, snapshot_gen=snapshot_gen,
            idem_key=idem_key, deadline_ms=deadline_ms, pod=pod,
            pod_blob=pod_blob)
        flags = 0
        if trace_ctx:
            body = framing.wrap_trace(body, trace_ctx)
            flags |= framing.FLAG_TRACE
        verb, payload = self._roundtrip(framing.BIND, body, flags=flags)
        if verb != framing.BIND_RESULT:
            raise WireError(f"unexpected verb 0x{verb:02x} to BIND")
        d = framing.decode_bind_result(payload)
        return BindResult(kind=d["kind"], error=d["error"],
                          retry_after_s=d["retry_after_ms"] / 1e3)

    def sync_nodes(self, nodes: List) -> int:
        return self._sync(framing.SYNC_NODES, nodes, "nodes")

    def sync_pods(self, pods: List) -> int:
        return self._sync(framing.SYNC_PODS, pods, "pods")

    def _sync(self, verb: int, items: List, kind: str) -> int:
        rverb, payload = self._roundtrip(
            verb, framing.encode_sync_request(items, kind))
        if rverb != framing.SYNCED:
            raise WireError(f"unexpected verb 0x{rverb:02x} to SYNC")
        return framing.decode_synced(payload)

    def relist(self) -> Tuple[List, List]:
        """Bounded-stale snapshot pull (ISSUE 16): (nodes, bound pods)
        from the shared cell's commit truth — a spawned scheduler
        process hydrates its local evaluator from this, then trues up
        with periodic re-pulls (its staleness window)."""
        verb, payload = self._roundtrip(framing.RELIST)
        if verb != framing.RELIST_RESULT:
            raise WireError(f"unexpected verb 0x{verb:02x} to RELIST")
        return framing.decode_relist_result(payload)

    def cell_agg(self, drain_spill: bool = False,
                 evacuate: bool = False) -> Tuple[dict, List]:
        """Federation pull (ISSUE 20): (aggregate dict, spilled pods) —
        the cell's routing column plus, with ``drain_spill``, the pods
        the cell gave up on (they LEFT its store with this response);
        ``evacuate`` additionally uproots every pending pod (brownout)."""
        verb, payload = self._roundtrip(
            framing.CELL_AGG,
            framing.encode_cell_agg_request(drain_spill, evacuate))
        if verb != framing.CELL_AGG_RESULT:
            raise WireError(f"unexpected verb 0x{verb:02x} to CELL_AGG")
        return framing.decode_cell_agg_result(payload)

    def admit(self, idem_key: str, pods: List) -> Tuple[int, int]:
        """Hand a batch of pending pods to this cell; (accepted,
        replayed). Replaying the SAME idem_key after an ambiguous wire
        fault converges to the recorded answer — the router's half of
        cross-cell exactly-once admission."""
        verb, payload = self._roundtrip(
            framing.ADMIT, framing.encode_admit_request(idem_key, pods))
        if verb != framing.ADMIT_RESULT:
            raise WireError(f"unexpected verb 0x{verb:02x} to ADMIT")
        return framing.decode_admit_result(payload)

    def metrics(self) -> str:
        verb, payload = self._roundtrip(framing.METRICS)
        if verb != framing.METRICS_TEXT:
            raise WireError(f"unexpected verb 0x{verb:02x} to METRICS")
        return framing.decode_metrics_text(payload)

    def stats(self, last: int = 0) -> dict:
        """Live introspection (ISSUE 13): {"vars": <registry snapshot>,
        "trace": [last N recorder events]} — the binary twin of HTTP
        /debug/vars + /debug/trace."""
        verb, payload = self._roundtrip(framing.STATS,
                                        framing.encode_stats_request(last))
        if verb != framing.STATS_RESULT:
            raise WireError(f"unexpected verb 0x{verb:02x} to STATS")
        return framing.decode_stats_result(payload)

    def __enter__(self) -> "BinaryWireClient":
        return self.connect()

    def __exit__(self, *exc) -> None:
        self.close()


__all__ = ["BinaryWireClient", "WireDeadline", "WireError",
           "WireOverloaded"]
