"""Runtime manager: the kubelet's bridge from Pod specs to the CRI.

The reference's kubeGenericRuntimeManager
(pkg/kubelet/kuberuntime/kuberuntime_manager.go) is the only code that
speaks both languages: it reads v1.Pod specs from above and drives the CRI
RuntimeService below. Its core is `computePodActions`
(kuberuntime_manager.go:414, acting on the podActions struct at :337):
given the pod spec and the runtime's observed state, decide — create a
sandbox? kill the pod? which containers to kill, which to start — then
SyncPod executes those actions in order (sandbox first, then containers).

RuntimeManager is that design over nodes/cri.py: pure decision
(`compute_pod_actions`) separated from execution (`sync_pod`), so any
RuntimeService implementation — the scripted fake, the process runtime —
gets identical lifecycle semantics. Restart-count bookkeeping rides the
CRI attempt counter (a same-named container re-created in the same sandbox
is attempt N+1), matching how the reference derives restart counts from
container attempts rather than keeping a side ledger.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.nodes.cri import (
    CREATED,
    EXITED,
    RUNNING,
    SANDBOX_READY,
    ContainerConfig,
    ContainerStatus,
    PodSandboxConfig,
    RuntimeService,
)

# Scripted-workload annotations (see nodes/kubelet.py module docstring).
RUN_SECONDS_ANNOTATION = "bench/run-seconds"
FAIL_ANNOTATION = "bench/fail"
IMAGE_SIZE_ANNOTATION = "bench/image-size"  # bytes per pulled image


@dataclass
class PodActions:
    """computePodActions' output (kuberuntime_manager.go:337 podActions)."""

    create_sandbox: bool = False
    sandbox_id: str = ""
    kill_pod: bool = False
    containers_to_start: List[ContainerConfig] = field(default_factory=list)
    containers_to_kill: List[str] = field(default_factory=list)


@dataclass
class PodRuntimeStatus:
    """The manager's aggregate view of one pod, derived purely from CRI
    statuses (the kubecontainer.PodStatus analog the kubelet's sync loop
    consumes)."""

    sandbox_id: str = ""
    exists: bool = False
    all_running: bool = False
    # "Succeeded"/"Failed" once every container ran to completion (scripted
    # run_seconds workloads); "" while anything still runs
    completed_phase: str = ""
    restarts: int = 0  # sum over containers of (attempt number)
    containers: List[ContainerStatus] = field(default_factory=list)


class RuntimeManager:
    def __init__(self, runtime: RuntimeService,
                 image_manager=None,
                 now: Callable[[], float] = time.monotonic):
        self.runtime = runtime
        self.images = image_manager
        self._now = now
        self._sandbox_ids: Dict[str, str] = {}  # pod key -> sandbox id

    # ---------------------------------------------------------- spec → CRI

    @staticmethod
    def container_configs(pod: Pod) -> List[ContainerConfig]:
        """Translate the pod's container specs to CRI container configs,
        attaching the scripted workload (run-seconds / fail) the hollow
        runtimes execute. A pod with no declared containers still gets one
        synthetic container — a sandbox with nothing in it is not a
        runnable pod."""
        run_s = pod.annotations.get(RUN_SECONDS_ANNOTATION)
        run_seconds = float(run_s) if run_s is not None else None
        fail = bool(pod.annotations.get(FAIL_ANNOTATION))
        specs = pod.containers or [None]
        out = []
        for i, c in enumerate(specs):
            name = c.name if c is not None and c.name else f"ctr-{i}"
            image = c.image if c is not None and c.image else "pause:latest"
            out.append(ContainerConfig(name=name, image=image,
                                       run_seconds=run_seconds,
                                       fail_exit=fail))
        return out

    # ------------------------------------------------------------ observe

    def pod_status(self, pod: Pod) -> PodRuntimeStatus:
        key = pod.key()
        sid = self._sandbox_ids.get(key)
        st = PodRuntimeStatus()
        if sid is None:
            return st
        sb = self.runtime.pod_sandbox_status(sid)
        if sb is None:
            return st
        st.sandbox_id = sid
        st.exists = True
        containers = self.runtime.list_containers(sandbox_id=sid)
        st.containers = containers
        # latest attempt per container name is the live one
        latest: Dict[str, ContainerStatus] = {}
        for c in containers:
            cur = latest.get(c.name)
            if cur is None or c.attempt > cur.attempt:
                latest[c.name] = c
        want = self.container_configs(pod)
        st.restarts = sum(c.attempt for c in latest.values())
        if latest and len(latest) == len(want) \
                and all(c.state == RUNNING for c in latest.values()):
            st.all_running = True
        if latest and len(latest) == len(want) \
                and all(c.state == EXITED for c in latest.values()):
            # natural completion only — a 137 means the kubelet killed it
            # (liveness restart in flight), not that the workload finished
            if all(c.exit_code != 137 for c in latest.values()):
                st.completed_phase = "Failed" if any(
                    c.exit_code != 0 for c in latest.values()) else "Succeeded"
            elif pod.restart_policy == "Never":
                # kubelet-killed (137) with restartPolicy Never: no fresh
                # attempt will ever start (compute_pod_actions refuses), so
                # without a terminal phase the pod would sit in the
                # kubelet's _starting set unready forever. The reference
                # resolves this in GetPhase (kuberuntime_manager.go /
                # kubelet_pods.go:1311): stopped containers that cannot
                # restart make the pod Failed.
                st.completed_phase = "Failed"
        return st

    # ------------------------------------------------------------- decide

    def compute_pod_actions(self, pod: Pod,
                            status: PodRuntimeStatus) -> PodActions:
        """kuberuntime_manager.go:414 computePodActions, for the hollow
        lifecycle: create the sandbox if absent; start any container whose
        latest attempt is missing; restart (start a fresh attempt of) a
        container the kubelet killed (exit 137) when restartPolicy allows;
        never restart a naturally-completed workload."""
        actions = PodActions(sandbox_id=status.sandbox_id)
        if not status.exists:
            actions.create_sandbox = True
            actions.containers_to_start = self.container_configs(pod)
            return actions
        latest: Dict[str, ContainerStatus] = {}
        for c in status.containers:
            cur = latest.get(c.name)
            if cur is None or c.attempt > cur.attempt:
                latest[c.name] = c
        for cfg in self.container_configs(pod):
            cur = latest.get(cfg.name)
            if cur is None:
                actions.containers_to_start.append(cfg)
            elif cur.state == EXITED and cur.exit_code == 137 \
                    and pod.restart_policy != "Never":
                actions.containers_to_start.append(cfg)
        return actions

    # ------------------------------------------------------------ execute

    def sync_pod(self, pod: Pod) -> PodActions:
        """SyncPod (kuberuntime_manager.go SyncPod): compute, then act —
        sandbox first, then image pulls, then container create+start."""
        status = self.pod_status(pod)
        actions = self.compute_pod_actions(pod, status)
        self.execute_pod_actions(pod, actions)
        return actions

    def execute_pod_actions(self, pod: Pod, actions: PodActions) -> None:
        """The action-execution half, split from decision so callers that
        already hold a fresh PodRuntimeStatus (the kubelet's per-step
        relist) don't pay a second status read just to find no-op."""
        sid = actions.sandbox_id
        if actions.create_sandbox:
            sid = self.runtime.run_pod_sandbox(PodSandboxConfig(
                name=pod.name, namespace=pod.namespace, uid=pod.uid,
                annotations=dict(pod.annotations)))
            self._sandbox_ids[pod.key()] = sid
        for cid in actions.containers_to_kill:
            self.runtime.stop_container(cid)
        for cfg in actions.containers_to_start:
            if self.images is not None:
                size = int(pod.annotations.get(IMAGE_SIZE_ANNOTATION, 0))
                self.images.ensure_image_exists(pod, cfg.image, size)
            cid = self.runtime.create_container(sid, cfg)
            self.runtime.start_container(cid)

    @staticmethod
    def actions_needed(actions: PodActions) -> bool:
        return bool(actions.create_sandbox or actions.containers_to_start
                    or actions.containers_to_kill)

    def restart_pod_containers(self, pod: Pod) -> None:
        """Kill the pod's running containers (liveness failure: the
        kubelet's restart is CRI kill + fresh attempt on the next sync —
        kuberuntime_manager.go SyncPod's kill-then-start path)."""
        key = pod.key()
        sid = self._sandbox_ids.get(key)
        if sid is None:
            return
        for c in self.runtime.list_containers(sandbox_id=sid):
            if c.state in (CREATED, RUNNING):
                self.runtime.stop_container(c.id)

    def kill_pod(self, pod_key: str) -> None:
        """Tear the pod down: stop + remove its sandbox (KillPod)."""
        sid = self._sandbox_ids.pop(pod_key, None)
        if sid is None:
            return
        self.runtime.stop_pod_sandbox(sid)
        self.runtime.remove_pod_sandbox(sid)

    def sandbox_ready(self, pod_key: str) -> bool:
        sid = self._sandbox_ids.get(pod_key)
        if sid is None:
            return False
        sb = self.runtime.pod_sandbox_status(sid)
        return sb is not None and sb.state == SANDBOX_READY
