"""Node agents (SURVEY.md §1 L6/L7): hollow kubelet fleet + proxy.

The kubemark design inverted: the reference runs REAL kubelet code against
fake externalities (cmd/kubemark/hollow-node.go); here the node agent is
hollow by construction — the pod lifecycle state machine, heartbeat loop,
node-side admission, and service routing are real, while the container
runtime is a latency-simulating fake (the NewFakeDockerClient EnableSleep
analog). One shared informer fans out to N kubelets (the scale answer to N
kubelets each holding a watch).
"""

from kubernetes_tpu.nodes.kubelet import HollowFleet, HollowKubelet
from kubernetes_tpu.nodes.proxy import HollowProxy

__all__ = ["HollowFleet", "HollowKubelet", "HollowProxy"]
