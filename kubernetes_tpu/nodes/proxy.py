"""Hollow proxy: the kube-proxy analog — Services/Endpoints -> routing table.

Mirrors pkg/proxy/iptables/proxier.go's shape without the kernel: every sync
is a FULL table rebuild from watched state (syncProxyRules at proxier.go:966
rewrites the whole KUBE-SERVICES chain each pass — same idiom here, a dict
swap), and routing picks a backend per connection. The reference's iptables
probability-based load balancing becomes deterministic round-robin.

The table is identical on every node (kube-proxy programs the same rules
fleet-wide), so one HollowProxy instance serves the whole hollow cluster.
"""

from __future__ import annotations

import threading
from kubernetes_tpu.analysis import lockcheck
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.client.informer import SharedInformerFactory

# route key: "<ns>/<service>:<port>" -> list of (ip, target_port, node_name)
Backend = Tuple[str, int, str]


class HollowProxy:
    def __init__(self, factory: SharedInformerFactory):
        self.svc_informer = factory.informer("Service")
        self.eps_informer = factory.informer("Endpoints")
        self._lock = lockcheck.make_lock("HollowProxy._lock")
        self._table: Dict[str, List[Backend]] = {}
        self._local_counts: Dict[str, Dict[str, int]] = {}
        self._rr: Dict[str, int] = {}
        self.sync_count = 0
        # any change triggers a full resync, proxier.go-style
        for inf in (self.svc_informer, self.eps_informer):
            inf.add_event_handler(
                on_add=lambda o: self.sync_rules(),
                on_update=lambda old, new: self.sync_rules(),
                on_delete=lambda o: self.sync_rules())

    def sync_rules(self) -> None:
        """Full-table rewrite from current Services x Endpoints."""
        eps_by_key = {e.key(): e for e in self.eps_informer.store.list()}
        table: Dict[str, List[Backend]] = {}
        local_counts: Dict[str, Dict[str, int]] = {}
        for svc in self.svc_informer.store.list():
            eps = eps_by_key.get(svc.key())
            backends_src = eps.addresses if eps else []
            # per-service per-node endpoint counts for the healthcheck
            # server (same for every port: one index, not a table scan)
            counts: Dict[str, int] = {}
            for a in backends_src:
                counts[a.node_name] = counts.get(a.node_name, 0) + 1
            local_counts[svc.key()] = counts
            for port in svc.ports or []:
                route_key = f"{svc.key()}:{port.port}"
                table[route_key] = [
                    (a.ip, port.target_port or port.port, a.node_name)
                    for a in backends_src]
        with self._lock:
            self._table = table
            self._local_counts = local_counts
            self.sync_count += 1

    def route(self, service_key: str, port: int) -> Optional[Backend]:
        """One connection: round-robin over ready backends (the userspace
        proxy's LoadBalancerRR, pkg/proxy/userspace/roundrobin.go)."""
        key = f"{service_key}:{port}"
        with self._lock:
            backends = self._table.get(key)
            if not backends:
                return None
            i = self._rr.get(key, 0) % len(backends)
            self._rr[key] = i + 1
            return backends[i]

    def backends(self, service_key: str, port: int) -> List[Backend]:
        with self._lock:
            return list(self._table.get(f"{service_key}:{port}", ()))

    def local_endpoint_count(self, service_key: str, node_name: str) -> int:
        """Backends of a service living on `node_name` — the quantity the
        healthcheck server reports (healthcheck.go hcPayload). O(1) from
        the per-service index sync_rules maintains."""
        with self._lock:
            return self._local_counts.get(service_key, {}).get(node_name, 0)


class ProxyHealthServer:
    """The proxy healthcheck server (pkg/proxy/healthcheck/healthcheck.go):
    external load balancers probe it to learn whether THIS node has local
    endpoints for a service (externalTrafficPolicy=Local). 200 + the local
    endpoint count when some exist, 503 when none — the LB then skips the
    node. One server per node; paths are /healthz/<ns>/<name> (the
    reference allocates one healthCheckNodePort per service; a path per
    service keeps the sim to one listener)."""

    def __init__(self, proxy: HollowProxy, node_name: str,
                 host: str = "127.0.0.1", port: int = 0):
        import json
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        outer = self
        self.proxy = proxy
        self.node_name = node_name

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_GET(self):
                parts = self.path.strip("/").split("/")
                if len(parts) != 3 or parts[0] != "healthz":
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                service_key = parts[1] + "/" + parts[2]
                n = outer.proxy.local_endpoint_count(service_key,
                                                     outer.node_name)
                body = json.dumps({"service": service_key,
                                   "localEndpoints": n}).encode()
                self.send_response(200 if n > 0 else 503)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
