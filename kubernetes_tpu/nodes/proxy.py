"""Hollow proxy: the kube-proxy analog — Services/Endpoints -> routing table.

Mirrors pkg/proxy/iptables/proxier.go's shape without the kernel: every sync
is a FULL table rebuild from watched state (syncProxyRules at proxier.go:966
rewrites the whole KUBE-SERVICES chain each pass — same idiom here, a dict
swap), and routing picks a backend per connection. The reference's iptables
probability-based load balancing becomes deterministic round-robin.

The table is identical on every node (kube-proxy programs the same rules
fleet-wide), so one HollowProxy instance serves the whole hollow cluster.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.client.informer import SharedInformerFactory

# route key: "<ns>/<service>:<port>" -> list of (ip, target_port, node_name)
Backend = Tuple[str, int, str]


class HollowProxy:
    def __init__(self, factory: SharedInformerFactory):
        self.svc_informer = factory.informer("Service")
        self.eps_informer = factory.informer("Endpoints")
        self._lock = threading.Lock()
        self._table: Dict[str, List[Backend]] = {}
        self._rr: Dict[str, int] = {}
        self.sync_count = 0
        # any change triggers a full resync, proxier.go-style
        for inf in (self.svc_informer, self.eps_informer):
            inf.add_event_handler(
                on_add=lambda o: self.sync_rules(),
                on_update=lambda old, new: self.sync_rules(),
                on_delete=lambda o: self.sync_rules())

    def sync_rules(self) -> None:
        """Full-table rewrite from current Services x Endpoints."""
        eps_by_key = {e.key(): e for e in self.eps_informer.store.list()}
        table: Dict[str, List[Backend]] = {}
        for svc in self.svc_informer.store.list():
            eps = eps_by_key.get(svc.key())
            backends_src = eps.addresses if eps else []
            for port in svc.ports or []:
                route_key = f"{svc.key()}:{port.port}"
                table[route_key] = [
                    (a.ip, port.target_port or port.port, a.node_name)
                    for a in backends_src]
        with self._lock:
            self._table = table
            self.sync_count += 1

    def route(self, service_key: str, port: int) -> Optional[Backend]:
        """One connection: round-robin over ready backends (the userspace
        proxy's LoadBalancerRR, pkg/proxy/userspace/roundrobin.go)."""
        key = f"{service_key}:{port}"
        with self._lock:
            backends = self._table.get(key)
            if not backends:
                return None
            i = self._rr.get(key, 0) % len(backends)
            self._rr[key] = i + 1
            return backends[i]

    def backends(self, service_key: str, port: int) -> List[Backend]:
        with self._lock:
            return list(self._table.get(f"{service_key}:{port}", ()))
