"""CRI-shaped container runtime boundary.

The reference kubelet talks to ANY container runtime through one interface
pair — RuntimeService (sandbox + container lifecycle) and ImageService
(pull/list/remove/fs-info) — defined in pkg/kubelet/apis/cri/services.go:33
(ContainerManager), :59 (PodSandboxManager), :89 (RuntimeService), :103
(ImageManagerService). Shims (dockershim/, rktshim/, remote/) implement it;
the kubelet's runtime manager (kuberuntime/) drives it and nothing above the
manager knows which runtime is behind it.

This module is that seam for the TPU build:

- `RuntimeService` / `ImageService`: the abstract boundary. In-process
  method calls stand in for the reference's gRPC hop — the contract (ids,
  states, attempt counters, idempotent stops) is what matters, not the
  transport; the hollow fleet runs 5k kubelets in one process and a gRPC
  round-trip per sandbox op would be pure overhead on the bench path.
- `FakeRuntimeService`: the kubemark move (NewFakeDockerClient,
  cmd/kubemark/hollow-node.go:119-121) — the hollow kubelet's previous
  inline annotation-scripted behavior, reimplemented BEHIND the interface.
  Boot latency and run-to-completion are simulated against the kubelet's
  (possibly fake) clock, so the virtual-clock tests keep working.
- `ProcessRuntimeService`: a second, real runtime — sandboxes and
  containers are actual OS processes (`build/bin/pause` when built, else
  /bin/sleep). It exists to prove the boundary: the kubelet runs against it
  with zero kubelet changes (tests/test_cri.py).

States mirror the CRI enums (PodSandboxState / ContainerState in the CRI
protobuf, pkg/kubelet/apis/cri/v1alpha1/runtime/): a sandbox is READY or
NOTREADY; a container is CREATED -> RUNNING -> EXITED.
"""

from __future__ import annotations

import abc
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

# Container states (CRI ContainerState enum).
CREATED = "created"
RUNNING = "running"
EXITED = "exited"

# Sandbox states (CRI PodSandboxState enum).
SANDBOX_READY = "ready"
SANDBOX_NOTREADY = "notready"


@dataclass
class PodSandboxConfig:
    """What the manager hands RunPodSandbox (CRI PodSandboxConfig): enough
    identity to find the sandbox again and the pod-level annotations the
    fake runtime scripts behavior from."""

    name: str = ""
    namespace: str = ""
    uid: str = ""
    annotations: Dict[str, str] = field(default_factory=dict)

    def pod_key(self) -> str:
        return self.namespace + "/" + self.name


@dataclass
class ContainerConfig:
    """CRI ContainerConfig reduced to what the hollow runtimes consume.
    run_seconds/fail_exit are the scripted workload (parsed from pod
    annotations by the manager, the way kubemark scripts its fake docker)."""

    name: str = ""
    image: str = ""
    run_seconds: Optional[float] = None
    fail_exit: bool = False


@dataclass
class PodSandboxStatus:
    id: str = ""
    state: str = SANDBOX_READY
    created_at: float = 0.0
    config: PodSandboxConfig = field(default_factory=PodSandboxConfig)


@dataclass
class ContainerStatus:
    """CRI ContainerStatus: the manager reads state/attempt/exit_code to
    compute pod phase and restart counts."""

    id: str = ""
    name: str = ""
    sandbox_id: str = ""
    image: str = ""
    state: str = CREATED
    attempt: int = 0
    created_at: float = 0.0
    # the instant it becomes RUNNING; None until StartContainer (a None
    # sentinel, not 0.0 — virtual test clocks legitimately start at 0.0)
    started_at: Optional[float] = None
    finished_at: float = 0.0
    exit_code: int = 0


@dataclass
class Image:
    """CRI Image (ImageService.ListImages element)."""

    ref: str = ""
    size_bytes: int = 0
    pulled_at: float = 0.0
    last_used_at: float = 0.0


class RuntimeService(abc.ABC):
    """Sandbox + container lifecycle (services.go:89 RuntimeService =
    PodSandboxManager + ContainerManager). All ops are idempotent where the
    CRI requires it (StopPodSandbox/StopContainer on an already-stopped
    target must not error)."""

    # -- PodSandboxManager (services.go:59) --------------------------------
    @abc.abstractmethod
    def run_pod_sandbox(self, config: PodSandboxConfig) -> str:
        """Create+start the pod-level sandbox; returns its id."""

    @abc.abstractmethod
    def stop_pod_sandbox(self, sandbox_id: str) -> None:
        """Stop the sandbox (and any containers in it). Idempotent."""

    @abc.abstractmethod
    def remove_pod_sandbox(self, sandbox_id: str) -> None:
        """Remove a stopped sandbox and its containers. Idempotent."""

    @abc.abstractmethod
    def pod_sandbox_status(self, sandbox_id: str) -> Optional[PodSandboxStatus]:
        pass

    @abc.abstractmethod
    def list_pod_sandboxes(self) -> List[PodSandboxStatus]:
        pass

    # -- ContainerManager (services.go:33) ---------------------------------
    @abc.abstractmethod
    def create_container(self, sandbox_id: str,
                         config: ContainerConfig) -> str:
        """Create (not start) a container in the sandbox; returns its id.
        The attempt counter is per (sandbox, container-name): creating a
        same-named container again is a restart."""

    @abc.abstractmethod
    def start_container(self, container_id: str) -> None:
        pass

    @abc.abstractmethod
    def stop_container(self, container_id: str) -> None:
        """Idempotent; an EXITED container stays EXITED."""

    @abc.abstractmethod
    def remove_container(self, container_id: str) -> None:
        pass

    @abc.abstractmethod
    def container_status(self, container_id: str) -> Optional[ContainerStatus]:
        pass

    @abc.abstractmethod
    def list_containers(self, sandbox_id: Optional[str] = None
                        ) -> List[ContainerStatus]:
        pass

    def version(self) -> str:
        return "0.1.0"

    # True when container exits only happen through scripted run_seconds
    # configs (the fake runtime): the kubelet then skips the per-step exit
    # poll for pods with no scripted exit — a real runtime's containers
    # can die at any time, so it stays False by default
    exits_are_scripted = False


class ImageService(abc.ABC):
    """Image lifecycle (services.go:103 ImageManagerService)."""

    @abc.abstractmethod
    def pull_image(self, ref: str, size_bytes: int = 0) -> str:
        pass

    @abc.abstractmethod
    def list_images(self) -> List[Image]:
        pass

    @abc.abstractmethod
    def remove_image(self, ref: str) -> None:
        pass

    @abc.abstractmethod
    def image_fs_info(self) -> int:
        """Total bytes used by images (CRI ImageFsInfo, collapsed to the
        one number ImageGC needs)."""


class FakeRuntimeService(RuntimeService, ImageService):
    """The hollow runtime: kubemark's scripted fake docker client behind
    the CRI boundary. Time-driven behavior is computed lazily from the
    injected clock so virtual-clock tests drive it:

    - a started container reports CREATED until `boot_latency` has elapsed
      since StartContainer, then RUNNING (the FakeDockerClient EnableSleep
      startup simulation, hollow-node.go:119-121)
    - a container whose config carries run_seconds reports EXITED (exit
      code 1 if fail_exit) once that long RUNNING
    """

    exits_are_scripted = True

    def __init__(self, boot_latency: float = 0.0,
                 now: Callable[[], float] = time.monotonic):
        self.boot_latency = boot_latency
        self._now = now
        self._sandboxes: Dict[str, PodSandboxStatus] = {}
        self._containers: Dict[str, ContainerStatus] = {}
        self._configs: Dict[str, ContainerConfig] = {}
        self._attempts: Dict[str, int] = {}  # (sandbox_id, name) -> count
        # sandbox id -> container ids, so per-pod relists are O(pod
        # containers) — a 5k-kubelet hollow fleet polls every pod every
        # step and a flat scan would make that quadratic
        self._by_sandbox: Dict[str, List[str]] = {}
        self._images: Dict[str, Image] = {}
        self._seq = 0
        self.ops: Dict[str, int] = {}  # op-name -> call count (test probe)

    def _id(self, prefix: str) -> str:
        self._seq += 1
        return f"{prefix}-{self._seq}"

    def _count(self, op: str) -> None:
        self.ops[op] = self.ops.get(op, 0) + 1

    # -- sandboxes ---------------------------------------------------------

    def run_pod_sandbox(self, config: PodSandboxConfig) -> str:
        self._count("RunPodSandbox")
        sid = self._id("sandbox")
        self._sandboxes[sid] = PodSandboxStatus(
            id=sid, state=SANDBOX_READY, created_at=self._now(),
            config=config)
        self._by_sandbox[sid] = []
        return sid

    def stop_pod_sandbox(self, sandbox_id: str) -> None:
        self._count("StopPodSandbox")
        sb = self._sandboxes.get(sandbox_id)
        if sb is None:
            return
        sb.state = SANDBOX_NOTREADY
        for cid in self._by_sandbox.get(sandbox_id, []):
            c = self._containers.get(cid)
            if c is not None:
                self._stop(c)

    def remove_pod_sandbox(self, sandbox_id: str) -> None:
        self._count("RemovePodSandbox")
        self._sandboxes.pop(sandbox_id, None)
        for cid in self._by_sandbox.pop(sandbox_id, []):
            self._containers.pop(cid, None)
            self._configs.pop(cid, None)

    def pod_sandbox_status(self, sandbox_id: str) -> Optional[PodSandboxStatus]:
        return self._sandboxes.get(sandbox_id)

    def list_pod_sandboxes(self) -> List[PodSandboxStatus]:
        return list(self._sandboxes.values())

    # -- containers --------------------------------------------------------

    def create_container(self, sandbox_id: str,
                         config: ContainerConfig) -> str:
        self._count("CreateContainer")
        if sandbox_id not in self._sandboxes:
            raise KeyError(f"no sandbox {sandbox_id!r}")
        cid = self._id("ctr")
        akey = sandbox_id + "/" + config.name
        attempt = self._attempts.get(akey, 0)
        self._attempts[akey] = attempt + 1
        self._containers[cid] = ContainerStatus(
            id=cid, name=config.name, sandbox_id=sandbox_id,
            image=config.image, state=CREATED, attempt=attempt,
            created_at=self._now())
        self._configs[cid] = config
        self._by_sandbox[sandbox_id].append(cid)
        img = self._images.get(config.image)
        if img is not None:
            img.last_used_at = self._now()
        return cid

    def start_container(self, container_id: str) -> None:
        self._count("StartContainer")
        c = self._containers[container_id]
        # becomes RUNNING at started_at; _refresh computes the lazy state
        c.started_at = self._now() + self.boot_latency

    def _stop(self, c: ContainerStatus) -> None:
        if c.state == EXITED:
            return
        self._refresh(c)
        if c.state == EXITED:
            return
        c.state = EXITED
        c.finished_at = self._now()
        c.exit_code = 137  # SIGKILLed, as docker reports a stopped container

    def stop_container(self, container_id: str) -> None:
        self._count("StopContainer")
        c = self._containers.get(container_id)
        if c is not None:
            self._stop(c)

    def remove_container(self, container_id: str) -> None:
        self._count("RemoveContainer")
        c = self._containers.pop(container_id, None)
        self._configs.pop(container_id, None)
        if c is not None and c.sandbox_id in self._by_sandbox:
            try:
                self._by_sandbox[c.sandbox_id].remove(container_id)
            except ValueError:
                pass

    def _refresh(self, c: ContainerStatus) -> None:
        """Advance the lazily-computed state to the current clock."""
        if c.state == EXITED:
            return
        now = self._now()
        if c.started_at is not None and now >= c.started_at:
            c.state = RUNNING
            cfg = self._configs.get(c.id)
            if cfg is not None and cfg.run_seconds is not None \
                    and now >= c.started_at + cfg.run_seconds:
                c.state = EXITED
                c.finished_at = c.started_at + cfg.run_seconds
                c.exit_code = 1 if cfg.fail_exit else 0

    def container_status(self, container_id: str) -> Optional[ContainerStatus]:
        c = self._containers.get(container_id)
        if c is not None:
            self._refresh(c)
        return c

    def list_containers(self, sandbox_id: Optional[str] = None
                        ) -> List[ContainerStatus]:
        if sandbox_id is not None:
            cids = self._by_sandbox.get(sandbox_id, [])
            out = [self._containers[cid] for cid in cids
                   if cid in self._containers]
        else:
            out = list(self._containers.values())
        for c in out:
            self._refresh(c)
        return out

    # -- images ------------------------------------------------------------

    def pull_image(self, ref: str, size_bytes: int = 0) -> str:
        self._count("PullImage")
        img = self._images.get(ref)
        if img is None:
            img = Image(ref=ref, size_bytes=size_bytes,
                        pulled_at=self._now())
            self._images[ref] = img
        img.last_used_at = self._now()
        return ref

    def list_images(self) -> List[Image]:
        return list(self._images.values())

    def remove_image(self, ref: str) -> None:
        self._count("RemoveImage")
        self._images.pop(ref, None)

    def image_fs_info(self) -> int:
        return sum(i.size_bytes for i in self._images.values())

    def images_in_use(self) -> set:
        """Image refs referenced by any non-removed container — protected
        from GC (image_gc_manager.go detectImages' imagesInUse)."""
        return {c.image for c in self._containers.values() if c.image}


class ProcessRuntimeService(RuntimeService, ImageService):
    """A real runtime behind the same boundary: every sandbox is a real
    `pause` process (build/bin/pause if compiled, else /bin/sleep) holding
    the pod's existence the way the reference's pause container holds its
    network namespace (build/pause/pause.c), and every container is a real
    child process. Proves the kubelet is runtime-agnostic; wall-clock only
    (real processes don't run on a virtual clock)."""

    def __init__(self, pause_path: Optional[str] = None):
        import os
        self._pause = pause_path
        if self._pause is None:
            cand = os.path.join(os.path.dirname(__file__), os.pardir,
                                os.pardir, "build", "bin", "pause")
            self._pause = cand if os.path.exists(cand) else None
        self._sandboxes: Dict[str, PodSandboxStatus] = {}
        self._procs: Dict[str, object] = {}  # sandbox/container id -> Popen
        self._containers: Dict[str, ContainerStatus] = {}
        self._configs: Dict[str, ContainerConfig] = {}
        self._attempts: Dict[str, int] = {}
        self._images: Dict[str, Image] = {}
        self._seq = 0

    def _id(self, prefix: str) -> str:
        self._seq += 1
        return f"{prefix}-{self._seq}"

    def _spawn(self, argv: List[str]):
        import subprocess
        return subprocess.Popen(
            argv, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    # -- sandboxes ---------------------------------------------------------

    def run_pod_sandbox(self, config: PodSandboxConfig) -> str:
        sid = self._id("sandbox")
        argv = [self._pause] if self._pause else ["/bin/sleep", "86400"]
        self._procs[sid] = self._spawn(argv)
        self._sandboxes[sid] = PodSandboxStatus(
            id=sid, state=SANDBOX_READY, created_at=time.monotonic(),
            config=config)
        return sid

    def stop_pod_sandbox(self, sandbox_id: str) -> None:
        sb = self._sandboxes.get(sandbox_id)
        if sb is None:
            return
        sb.state = SANDBOX_NOTREADY
        for cid, c in self._containers.items():
            if c.sandbox_id == sandbox_id:
                self.stop_container(cid)
        self._kill(sandbox_id)

    def remove_pod_sandbox(self, sandbox_id: str) -> None:
        self.stop_pod_sandbox(sandbox_id)
        self._sandboxes.pop(sandbox_id, None)
        for cid in [cid for cid, c in self._containers.items()
                    if c.sandbox_id == sandbox_id]:
            self._containers.pop(cid)
            self._configs.pop(cid, None)
            self._procs.pop(cid, None)

    def pod_sandbox_status(self, sandbox_id: str) -> Optional[PodSandboxStatus]:
        return self._sandboxes.get(sandbox_id)

    def list_pod_sandboxes(self) -> List[PodSandboxStatus]:
        return list(self._sandboxes.values())

    def _kill(self, proc_id: str) -> None:
        proc = self._procs.get(proc_id)
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait()

    # -- containers --------------------------------------------------------

    def create_container(self, sandbox_id: str,
                         config: ContainerConfig) -> str:
        if sandbox_id not in self._sandboxes:
            raise KeyError(f"no sandbox {sandbox_id!r}")
        cid = self._id("ctr")
        akey = sandbox_id + "/" + config.name
        attempt = self._attempts.get(akey, 0)
        self._attempts[akey] = attempt + 1
        self._containers[cid] = ContainerStatus(
            id=cid, name=config.name, sandbox_id=sandbox_id,
            image=config.image, state=CREATED, attempt=attempt,
            created_at=time.monotonic())
        self._configs[cid] = config
        return cid

    def start_container(self, container_id: str) -> None:
        cfg = self._configs[container_id]
        run_s = cfg.run_seconds if cfg.run_seconds is not None else 86400
        argv = ["/bin/sh", "-c",
                f"sleep {run_s}; exit {1 if cfg.fail_exit else 0}"]
        self._procs[container_id] = self._spawn(argv)
        c = self._containers[container_id]
        c.state = RUNNING
        c.started_at = time.monotonic()

    def stop_container(self, container_id: str) -> None:
        c = self._containers.get(container_id)
        if c is None or c.state == EXITED:
            return
        self._refresh(c)
        if c.state == EXITED:
            return
        self._kill(container_id)
        c.state = EXITED
        c.finished_at = time.monotonic()
        c.exit_code = 137

    def remove_container(self, container_id: str) -> None:
        self.stop_container(container_id)
        self._containers.pop(container_id, None)
        self._configs.pop(container_id, None)
        self._procs.pop(container_id, None)

    def _refresh(self, c: ContainerStatus) -> None:
        if c.state != RUNNING:
            return
        proc = self._procs.get(c.id)
        if proc is not None:
            rc = proc.poll()
            if rc is not None:
                c.state = EXITED
                c.finished_at = time.monotonic()
                c.exit_code = rc

    def container_status(self, container_id: str) -> Optional[ContainerStatus]:
        c = self._containers.get(container_id)
        if c is not None:
            self._refresh(c)
        return c

    def list_containers(self, sandbox_id: Optional[str] = None
                        ) -> List[ContainerStatus]:
        out = []
        for c in self._containers.values():
            if sandbox_id is not None and c.sandbox_id != sandbox_id:
                continue
            self._refresh(c)
            out.append(c)
        return out

    # -- images (instant pulls; a process runtime has no registry) ---------

    def pull_image(self, ref: str, size_bytes: int = 0) -> str:
        if ref not in self._images:
            self._images[ref] = Image(ref=ref, size_bytes=size_bytes,
                                      pulled_at=time.monotonic())
        return ref

    def list_images(self) -> List[Image]:
        return list(self._images.values())

    def remove_image(self, ref: str) -> None:
        self._images.pop(ref, None)

    def image_fs_info(self) -> int:
        return sum(i.size_bytes for i in self._images.values())

    def images_in_use(self) -> set:
        return {c.image for c in self._containers.values() if c.image}

    def close(self) -> None:
        """Kill every process this runtime spawned (test teardown)."""
        for pid in list(self._procs):
            self._kill(pid)
        self._procs.clear()
