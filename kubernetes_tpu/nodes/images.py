"""Image manager + image GC behind the CRI ImageService.

Two reference components live here:

- ImageManager (pkg/kubelet/images/image_manager.go EnsureImageExists):
  the pull-policy gate in front of every container start — Always pulls,
  IfNotPresent pulls only when absent, Never errors when absent.
- ImageGCManager (pkg/kubelet/images/image_gc_manager.go:41, policy
  thresholds validated at :133-140, GarbageCollect at :245): when the
  image filesystem crosses HighThresholdPercent, delete
  least-recently-used images that no container references until usage is
  back under LowThresholdPercent. The kubelet's eviction manager calls
  this FIRST when it sees disk pressure — reclaiming node-level resources
  before killing pods (eviction_manager.go reclaimNodeLevelResources).

Pull policy rides the pod annotation `bench/image-pull-policy` (the hollow
analog of v1.Container.ImagePullPolicy; one knob per pod keeps the scripted
surface small), defaulting to IfNotPresent like the reference does for
tagged images.
"""

from __future__ import annotations

from typing import List

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.nodes.cri import ImageService

PULL_POLICY_ANNOTATION = "bench/image-pull-policy"

PULL_ALWAYS = "Always"
PULL_IF_NOT_PRESENT = "IfNotPresent"
PULL_NEVER = "Never"


class ImagePullError(Exception):
    pass


class ImageManager:
    """EnsureImageExists (image_manager.go): one decision per container
    start."""

    def __init__(self, service: ImageService):
        self.service = service
        self.pulls = 0  # diagnostics

    def ensure_image_exists(self, pod: Pod, image: str,
                            size_bytes: int = 0) -> None:
        policy = pod.annotations.get(PULL_POLICY_ANNOTATION,
                                     PULL_IF_NOT_PRESENT)
        present = any(i.ref == image for i in self.service.list_images())
        if policy == PULL_NEVER:
            if not present:
                raise ImagePullError(
                    f"container image {image!r} is not present with pull "
                    f"policy of Never")
            return
        if policy == PULL_ALWAYS or not present:
            self.service.pull_image(image, size_bytes=size_bytes)
            self.pulls += 1


class ImageGCPolicy:
    """image_gc_manager.go:55 ImageGCPolicy with the same validation
    (:133-140): percents in [0,100], low <= high."""

    def __init__(self, high_threshold_percent: int = 85,
                 low_threshold_percent: int = 80):
        if not 0 <= high_threshold_percent <= 100:
            raise ValueError(
                f"invalid HighThresholdPercent {high_threshold_percent}, "
                f"must be in range [0-100]")
        if not 0 <= low_threshold_percent <= 100:
            raise ValueError(
                f"invalid LowThresholdPercent {low_threshold_percent}, "
                f"must be in range [0-100]")
        if low_threshold_percent > high_threshold_percent:
            raise ValueError(
                f"LowThresholdPercent {low_threshold_percent} can not be "
                f"higher than HighThresholdPercent {high_threshold_percent}")
        self.high = high_threshold_percent
        self.low = low_threshold_percent


class ImageGCManager:
    """image_gc_manager.go:41: threshold-triggered LRU image deletion.
    `capacity_bytes` is the image filesystem size (cadvisor ImagesFsInfo
    in the reference; a configured number in the hollow node)."""

    def __init__(self, service: ImageService, capacity_bytes: int,
                 policy: ImageGCPolicy = None):
        self.service = service
        self.capacity = capacity_bytes
        self.policy = policy or ImageGCPolicy()
        self.freed_total = 0  # diagnostics

    def _in_use(self) -> set:
        in_use = getattr(self.service, "images_in_use", None)
        return in_use() if in_use is not None else set()

    def garbage_collect(self) -> int:
        """One GC pass; returns bytes freed. Mirrors GarbageCollect
        (:245): compute usage percent; above high → free down to low by
        deleting unused images oldest-last-used first."""
        if self.capacity <= 0:
            return 0
        usage = self.service.image_fs_info()
        if usage * 100 < self.policy.high * self.capacity:
            return 0
        target = self.capacity * self.policy.low // 100
        return self.free_space(usage - target)

    def free_space(self, bytes_to_free: int) -> int:
        """freeSpace (image_gc_manager.go:277): delete unused images in
        last-used order until `bytes_to_free` is reclaimed or candidates
        run out. Also the eviction manager's disk-reclaim hook."""
        if bytes_to_free <= 0:
            return 0
        in_use = self._in_use()
        candidates: List = [i for i in self.service.list_images()
                            if i.ref not in in_use]
        candidates.sort(key=lambda i: i.last_used_at)
        freed = 0
        for img in candidates:
            if freed >= bytes_to_free:
                break
            self.service.remove_image(img.ref)
            freed += img.size_bytes
        self.freed_total += freed
        return freed
