"""The kubelet API server — pkg/kubelet/server/server.go.

The reference kubelet serves its own HTTP API next to the apiserver:
/healthz, /pods (the admitted pod set, used by the node problem
detector and debugging), /stats (cadvisor summaries),
/containerLogs/<ns>/<pod>/<container> (what `kubectl logs` proxies to),
and the streaming exec/attach/portForward endpoints
(server.go InstallDefaultHandlers + InstallDebuggingHandlers).

The hollow runtime has no real containers, so logs and exec are served
from the same annotation-scripted substrate the probes use:

  bench/log-lines=<text>   newline-separated synthetic log content
  bench/exec-<cmd>=<out>   canned output for `exec <cmd>`

which preserves the wire shape (URL layout, 404-vs-200 semantics,
follow=false reads) without inventing a container runtime — the same
trade kubemark makes with its fake docker client.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

LOG_LINES_ANNOTATION = "bench/log-lines"
EXEC_PREFIX_ANNOTATION = "bench/exec-"


class KubeletApiError(Exception):
    """HTTP-shaped kubelet API failure (code + message), raised by the
    HollowKubelet serve_* methods and mapped to a status by whichever
    transport carries it (HTTP here, SystemExit in ktctl)."""

    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code


class KubeletServer:
    """HTTP facade over one HollowKubelet (server.go Server)."""

    def __init__(self, kubelet, host: str = "127.0.0.1", port: int = 0):
        self.kubelet = kubelet
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _send(self, code: int, payload, ctype="application/json"):
                body = payload if isinstance(payload, bytes) else \
                    json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                url = urlparse(self.path)
                parts = [p for p in url.path.split("/") if p]
                k = outer.kubelet
                if url.path == "/healthz":
                    return self._send(200, b"ok", "text/plain")
                if url.path == "/pods":
                    return self._send(200, {"items": k.serve_pods()})
                if url.path == "/stats/summary":
                    return self._send(200, k.serve_stats())
                if parts[:1] == ["portForward"] and len(parts) >= 3:
                    # /portForward/<ns>/<pod>?port=N — one stream round
                    q = parse_qs(url.query)
                    try:
                        port = int(q.get("port", ["0"])[0])
                        data = k.serve_port(parts[1], parts[2], port)
                    except KubeletApiError as e:
                        return self._send(e.code, {"message": str(e)})
                    except ValueError:
                        return self._send(400, {"message": "bad port"})
                    return self._send(200, data,
                                      "application/octet-stream")
                if parts[:1] == ["containerLogs"] and len(parts) >= 3:
                    # /containerLogs/<ns>/<pod>[/<container>]
                    q = parse_qs(url.query)
                    try:
                        text = k.serve_logs(
                            parts[1], parts[2],
                            tail=q.get("tailLines", [None])[0])
                    except KubeletApiError as e:
                        return self._send(e.code, {"message": str(e)})
                    return self._send(200, text.encode(), "text/plain")
                return self._send(404, {"message": self.path})

            def do_POST(self):
                url = urlparse(self.path)
                parts = [p for p in url.path.split("/") if p]
                k = outer.kubelet
                if parts[:1] == ["attach"] and len(parts) >= 3:
                    # /attach/<ns>/<pod> — the running container's stream
                    try:
                        out = k.serve_attach(parts[1], parts[2])
                    except KubeletApiError as e:
                        return self._send(e.code, {"message": str(e)})
                    return self._send(200, out.encode(), "text/plain")
                if parts[:1] == ["exec"] and len(parts) >= 3:
                    # /exec/<ns>/<pod>?command=<cmd> (the non-streaming
                    # half of the exec contract; SPDY upgrade elided)
                    cmd = parse_qs(url.query).get("command", [""])[0]
                    try:
                        out = k.serve_exec(parts[1], parts[2], cmd)
                    except KubeletApiError as e:
                        return self._send(e.code, {"message": str(e)})
                    return self._send(200, out.encode(), "text/plain")
                return self._send(404, {"message": self.path})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
