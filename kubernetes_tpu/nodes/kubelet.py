"""Hollow kubelet: the node agent's pod lifecycle + status machinery.

What is mirrored from pkg/kubelet (kubelet.go syncLoop/syncPod and kubemark's
hollow_kubelet.go):

- consume bound pods for this node from the watch stream (the apiserver pod
  source, pkg/kubelet/config/apiserver.go)
- node-side admission re-running GeneralPredicates against local state
  (kubelet lifecycle handler, pkg/kubelet/lifecycle/predicate.go) — a pod the
  scheduler raced onto a full node goes Failed/OutOfResources, it does not run
- pod startup: Pending -> Running after a simulated runtime latency (the
  kubemark FakeDockerClient EnableSleep behavior,
  cmd/kubemark/hollow-node.go:119-121)
- run-to-completion: pods annotated `bench/run-seconds` go Succeeded (or
  Failed via `bench/fail`) when their runtime elapses — restartPolicy Never
  semantics for Job benchmarking
- status loop: heartbeat on the Node object (status manager + node status
  update, kubelet.go:1255 Run's updateRuntimeUp/syncNodeStatus)

HollowFleet multiplexes one informer across N kubelets by node-name index —
5k kubelets cost one watch cursor, the way kubemark's shared apiserver watch
cache absorbs 5k real watches.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional

from kubernetes_tpu.api.types import (
    ConditionStatus,
    Node,
    NodeCondition,
    Pod,
)
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.server.apiserver_lite import ApiServerLite, Conflict, NotFound

RUN_SECONDS_ANNOTATION = "bench/run-seconds"
FAIL_ANNOTATION = "bench/fail"


class HollowKubelet:
    def __init__(self, api: ApiServerLite, node: Node,
                 startup_latency: float = 0.0,
                 now: Callable[[], float] = time.monotonic):
        self.api = api
        self.node_name = node.name
        self._template = node
        self._now = now
        self.startup_latency = startup_latency
        # pod key -> ready_at (startup in flight)
        self._starting: Dict[str, float] = {}
        # pod key -> finish_at (run-to-completion in flight)
        self._running_until: Dict[str, float] = {}
        self._admitted: Dict[str, Pod] = {}  # local running set

    # ----------------------------------------------------------- node status

    def register(self) -> None:
        """Initial node registration (kubelet registerWithAPIServer)."""
        node = dataclasses.replace(self._template, heartbeat=self._now())
        try:
            self.api.create("Node", node)
        except Conflict:
            self.heartbeat()

    def heartbeat(self) -> None:
        """syncNodeStatus: bump heartbeat + assert Ready."""
        try:
            cur: Node = self.api.get("Node", "", self.node_name)
        except NotFound:
            return
        conds = [c for c in cur.conditions if c.type != "Ready"]
        conds.append(NodeCondition("Ready", ConditionStatus.TRUE))
        self.api.update("Node", dataclasses.replace(
            cur, heartbeat=self._now(), conditions=conds))

    # ------------------------------------------------------------- pod flow

    def _local_usage(self) -> tuple:
        cpu = mem = count = 0
        for p in self._admitted.values():
            r = p.resource_request()
            cpu += r.milli_cpu
            mem += r.memory
            count += 1
        return cpu, mem, count

    def _admit(self, pod: Pod) -> Optional[str]:
        """GeneralPredicates node-side: capacity re-check against local state
        (lifecycle/predicate.go). Returns rejection reason or None."""
        r = pod.resource_request()
        cpu, mem, count = self._local_usage()
        alloc = self._template.allocatable
        if count + 1 > self._template.allowed_pod_number:
            return "OutOfPods"
        if cpu + r.milli_cpu > alloc.milli_cpu:
            return "OutOfcpu"
        if mem + r.memory > alloc.memory:
            return "OutOfmemory"
        return None

    def handle_pod(self, pod: Pod) -> None:
        """A bound pod appeared/changed for this node (syncLoopIteration
        ADD/UPDATE)."""
        key = pod.key()
        if pod.phase in ("Succeeded", "Failed"):
            self._forget(key)
            return
        if key in self._admitted or key in self._starting:
            return
        reason = self._admit(pod)
        if reason is not None:
            self._set_phase(pod, "Failed", reason)
            return
        self._admitted[key] = pod
        self._starting[key] = self._now() + self.startup_latency

    def forget_pod(self, pod: Pod) -> None:
        """Pod deleted from the apiserver (kubelet HandlePodRemoves)."""
        self._forget(pod.key())

    def _forget(self, key: str) -> None:
        self._admitted.pop(key, None)
        self._starting.pop(key, None)
        self._running_until.pop(key, None)

    def step(self) -> int:
        """One PLEG relist: advance startups and completions. Returns number
        of status transitions written."""
        now = self._now()
        wrote = 0
        for key, ready_at in list(self._starting.items()):
            if now < ready_at:
                continue
            del self._starting[key]
            pod = self._admitted.get(key)
            if pod is None:
                continue
            run_s = pod.annotations.get(RUN_SECONDS_ANNOTATION)
            if self._set_phase(pod, "Running"):
                wrote += 1
            if run_s is not None:
                self._running_until[key] = now + float(run_s)
        for key, done_at in list(self._running_until.items()):
            if now < done_at:
                continue
            del self._running_until[key]
            pod = self._admitted.pop(key, None)
            if pod is None:
                continue
            final = "Failed" if pod.annotations.get(FAIL_ANNOTATION) else "Succeeded"
            if self._set_phase(pod, final):
                wrote += 1
        return wrote

    def _set_phase(self, pod: Pod, phase: str, reason: str = "") -> bool:
        """Status-manager PATCH with conflict retry."""
        for _ in range(3):
            try:
                cur: Pod = self.api.get("Pod", pod.namespace, pod.name)
            except NotFound:
                self._forget(pod.key())
                return False
            if cur.node_name != self.node_name:
                return False  # rebound elsewhere
            ann = dict(cur.annotations)
            if reason:
                ann["kubernetes.io/failure-reason"] = reason
            try:
                self.api.update("Pod", dataclasses.replace(
                    cur, phase=phase, annotations=ann),
                    expect_rv=cur.resource_version)
                return True
            except Conflict:
                continue
            except NotFound:
                return False
        return False


class HollowFleet:
    """N hollow kubelets behind ONE pod informer (by-node index dispatch)."""

    def __init__(self, api: ApiServerLite, factory: SharedInformerFactory,
                 startup_latency: float = 0.0,
                 now: Callable[[], float] = time.monotonic):
        self.api = api
        self._now = now
        self.startup_latency = startup_latency
        self.kubelets: Dict[str, HollowKubelet] = {}
        self.pod_informer = factory.informer("Pod")
        self.pod_informer.add_event_handler(
            on_add=self._dispatch_add,
            on_update=self._dispatch_update,
            on_delete=self._dispatch_delete)

    def add_node(self, node: Node, register: bool = True) -> HollowKubelet:
        kl = HollowKubelet(self.api, node,
                           startup_latency=self.startup_latency, now=self._now)
        self.kubelets[node.name] = kl
        if register:
            kl.register()
        return kl

    def _dispatch_add(self, pod: Pod) -> None:
        if pod.node_name and pod.node_name in self.kubelets:
            self.kubelets[pod.node_name].handle_pod(pod)

    def _dispatch_update(self, old: Pod, new: Pod) -> None:
        if old.node_name and old.node_name != new.node_name \
                and old.node_name in self.kubelets:
            self.kubelets[old.node_name].forget_pod(old)
        self._dispatch_add(new)

    def _dispatch_delete(self, pod: Pod) -> None:
        if pod.node_name and pod.node_name in self.kubelets:
            self.kubelets[pod.node_name].forget_pod(pod)

    def step(self) -> int:
        """Advance every kubelet's pod state machines."""
        return sum(kl.step() for kl in self.kubelets.values())

    def heartbeat_all(self) -> None:
        for kl in self.kubelets.values():
            kl.heartbeat()
