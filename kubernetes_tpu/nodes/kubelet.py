"""Hollow kubelet: the node agent's pod lifecycle + status machinery.

What is mirrored from pkg/kubelet (kubelet.go syncLoop/syncPod and kubemark's
hollow_kubelet.go):

- consume bound pods for this node from the watch stream (the apiserver pod
  source, pkg/kubelet/config/apiserver.go), merged with STATIC pod sources
  (file/dict manifests, pkg/kubelet/config/file.go) that surface as mirror
  pods on the apiserver (kubelet.go mirror-pod handling)
- per-pod serialized workers with latest-wins coalescing (PodWorkers;
  pkg/kubelet/pod_workers.go managePodLoop/UpdatePod)
- node-side admission re-running GeneralPredicates against local state
  (kubelet lifecycle handler, pkg/kubelet/lifecycle/predicate.go) — a pod the
  scheduler raced onto a full node goes Failed/OutOfResources, it does not run
- pod startup: Pending -> Running after a simulated runtime latency (the
  kubemark FakeDockerClient EnableSleep behavior,
  cmd/kubemark/hollow-node.go:119-121)
- liveness/readiness probes (ProberManager; pkg/kubelet/prober/
  prober_manager.go + worker.go): readiness outcomes flip the pod's Ready
  condition (gating Endpoints membership), liveness failures past
  FailureThreshold restart the container per restartPolicy (restart_count++)
  or fail the pod (Never)
- resource-pressure eviction (EvictionManager; pkg/kubelet/eviction/
  eviction_manager.go): usage signals above threshold set the node's
  MemoryPressure/DiskPressure conditions (which CheckNodeMemoryPressure /
  CheckNodeDiskPressure read scheduler-side) and evict pods in QoS order
  (BestEffort -> Burstable by usage-over-request -> Guaranteed) until the
  signal clears
- run-to-completion: pods annotated `bench/run-seconds` go Succeeded (or
  Failed via `bench/fail`) when their runtime elapses — restartPolicy Never
  semantics for Job benchmarking
- status loop: heartbeat on the Node object (status manager + node status
  update, kubelet.go:1255 Run's updateRuntimeUp/syncNodeStatus), now
  carrying the pressure conditions

Probe/usage outcomes in the hollow runtime are annotation-driven, the way
kubemark's FakeDockerClient scripts runtime behavior:
  bench/ready-after=<s>      readiness False until s seconds post-start
  bench/liveness-fail-at=<s> liveness starts failing s seconds post-start
  bench/actual-mem=<bytes>   working-set bytes the pod "really" uses
  bench/actual-disk=<bytes>  disk bytes the pod "really" uses

HollowFleet multiplexes one informer across N kubelets by node-name index —
5k kubelets cost one watch cursor, the way kubemark's shared apiserver watch
cache absorbs 5k real watches.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.api.types import (
    ConditionStatus,
    Node,
    NodeCondition,
    Pod,
    Resource,
)
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.server.apiserver_lite import ApiServerLite, Conflict, NotFound

# scripted-workload annotation keys live with the runtime manager that
# consumes them (nodes/kuberuntime.py); re-exported for compatibility
from kubernetes_tpu.nodes.kuberuntime import (  # noqa: F401
    FAIL_ANNOTATION,
    RUN_SECONDS_ANNOTATION,
)

READY_AFTER_ANNOTATION = "bench/ready-after"
LIVENESS_FAIL_AT_ANNOTATION = "bench/liveness-fail-at"
ACTUAL_MEM_ANNOTATION = "bench/actual-mem"
ACTUAL_DISK_ANNOTATION = "bench/actual-disk"
MIRROR_ANNOTATION = "kubernetes.io/config.mirror"


class PodWorkers:
    """Per-pod serialized sync with latest-wins coalescing — the semantics
    of pod_workers.go: one worker per pod; an update arriving while a sync
    is in flight replaces any still-pending update (UpdatePod :158-196);
    the worker drains until no pending update remains."""

    def __init__(self, sync_fn: Callable[[Pod, str], None]):
        self._sync = sync_fn
        self._pending: Dict[str, Tuple[Pod, str]] = {}
        self._working: set = set()
        self.syncs = 0  # diagnostics
        self.coalesced = 0

    def update_pod(self, pod: Pod, op: str) -> None:
        key = pod.key()
        if key in self._pending:
            self.coalesced += 1
        self._pending[key] = (pod, op)

    def forget(self, pod_key: str) -> None:
        self._pending.pop(pod_key, None)

    def drain(self) -> int:
        """Run every pod's pending sync exactly once (one pass = one
        managePodLoop wakeup per pod); re-queued work waits for the next
        drain, preserving per-pod serialization."""
        n = 0
        work = list(self._pending.items())
        self._pending.clear()
        for key, (pod, op) in work:
            if key in self._working:  # re-entrancy guard
                self._pending[key] = (pod, op)
                continue
            self._working.add(key)
            try:
                self._sync(pod, op)
                self.syncs += 1
                n += 1
            finally:
                self._working.discard(key)
        return n


class _ProbeState:
    __slots__ = ("started_at", "failures", "successes", "ready", "next_at")

    def __init__(self, started_at: float):
        self.started_at = started_at
        self.failures = 0
        self.successes = 0
        self.ready = False
        self.next_at: Optional[float] = None  # next probe instant


class ProberManager:
    """Liveness + readiness workers for one kubelet's admitted pods
    (prober_manager.go AddPod/RemovePod; worker.go probe loop). Outcomes
    come from the pod's bench/* annotations (hollow runtime)."""

    def __init__(self, now: Callable[[], float]):
        self._now = now
        self._liveness: Dict[str, _ProbeState] = {}
        self._readiness: Dict[str, _ProbeState] = {}

    def add_pod(self, pod: Pod, started_at: float) -> None:
        key = pod.key()
        for c in pod.containers:
            if c.liveness_probe is not None:
                self._liveness[key] = _ProbeState(started_at)
            if c.readiness_probe is not None:
                self._readiness[key] = _ProbeState(started_at)

    def remove_pod(self, pod_key: str) -> None:
        self._liveness.pop(pod_key, None)
        self._readiness.pop(pod_key, None)

    @staticmethod
    def _due(st: _ProbeState, spec, now: float) -> bool:
        """PeriodSeconds gating (worker.go's probe ticker): a probe fires at
        started_at+initial_delay, then every period_s — regardless of how
        often the sync loop runs."""
        if now < st.started_at + spec.initial_delay_s:
            return False
        if st.next_at is None:
            st.next_at = st.started_at + spec.initial_delay_s
        if now < st.next_at:
            return False
        # catch up to the present without replaying missed periods (the
        # worker runs one probe per wakeup, late or not)
        st.next_at = now + spec.period_s
        return True

    def has_readiness(self, pod_key: str) -> bool:
        return pod_key in self._readiness

    @staticmethod
    def _probe_spec(pod: Pod, liveness: bool):
        for c in pod.containers:
            p = c.liveness_probe if liveness else c.readiness_probe
            if p is not None:
                return p
        return None

    def tick(self, pod: Pod) -> Tuple[Optional[bool], Optional[bool]]:
        """(ready, live) for the pod at this instant; None = no probe of
        that kind. Thresholds per worker.go: a state flips only after
        FailureThreshold consecutive failures / SuccessThreshold
        successes."""
        key = pod.key()
        now = self._now()
        ready = live = None
        rs = self._readiness.get(key)
        if rs is not None:
            spec = self._probe_spec(pod, liveness=False)
            if self._due(rs, spec, now):
                ready_after = float(pod.annotations.get(
                    READY_AFTER_ANNOTATION, 0.0))
                ok = now >= rs.started_at + ready_after
                if ok:
                    rs.successes += 1
                    rs.failures = 0
                    if rs.successes >= spec.success_threshold:
                        rs.ready = True
                else:
                    rs.failures += 1
                    rs.successes = 0
                    if rs.failures >= spec.failure_threshold:
                        rs.ready = False
            ready = rs.ready
        ls = self._liveness.get(key)
        if ls is not None:
            spec = self._probe_spec(pod, liveness=True)
            if self._due(ls, spec, now):
                fail_at = pod.annotations.get(LIVENESS_FAIL_AT_ANNOTATION)
                failing = fail_at is not None \
                    and now >= ls.started_at + float(fail_at)
                if failing:
                    ls.failures += 1
                else:
                    ls.failures = 0
            live = ls.failures < spec.failure_threshold
        return ready, live

    def restart(self, pod: Pod, started_at: float) -> None:
        """Container restarted: probe state restarts with it (worker.go
        onHoldUntil + fresh result window)."""
        key = pod.key()
        if key in self._liveness:
            self._liveness[key] = _ProbeState(started_at)
        if key in self._readiness:
            self._readiness[key] = _ProbeState(started_at)


# eviction-hard thresholds, as fractions of allocatable (the shape of
# --eviction-hard=memory.available<X,nodefs.available<Y;
# eviction/eviction_manager.go synchronize + helpers.go thresholds)
DEFAULT_MEMORY_EVICTION_FRACTION = 0.95
DEFAULT_DISK_EVICTION_FRACTION = 0.95


class EvictionManager:
    """Pressure detection + QoS-ranked pod eviction for one node
    (eviction_manager.go:synchronize). Usage signals are the sum of the
    admitted pods' bench/actual-* annotations (fallback: their requests)."""

    def __init__(self, node: Node,
                 memory_fraction: float = DEFAULT_MEMORY_EVICTION_FRACTION,
                 disk_fraction: float = DEFAULT_DISK_EVICTION_FRACTION):
        self._alloc_mem = node.allocatable.memory
        self._alloc_disk = node.allocatable.storage_scratch
        self.memory_limit = int(self._alloc_mem * memory_fraction)
        self.disk_limit = int(self._alloc_disk * disk_fraction) \
            if self._alloc_disk else 0
        self.memory_pressure = False
        self.disk_pressure = False

    @staticmethod
    def _pod_usage(pod: Pod) -> Tuple[int, int]:
        req = pod.resource_request()
        mem = int(pod.annotations.get(ACTUAL_MEM_ANNOTATION, req.memory))
        disk = int(pod.annotations.get(ACTUAL_DISK_ANNOTATION,
                                       req.storage_scratch))
        return mem, disk

    @staticmethod
    def _qos_rank(pod: Pod, usage: int, request: int) -> Tuple[int, int]:
        """Eviction order (eviction/helpers.go rankMemoryPressure for 1.7:
        QoS class first — BestEffort, Burstable, Guaranteed — then usage
        above the MATCHING resource's request, descending)."""
        if pod.is_best_effort():
            qos = 0
        elif any(c.requests and c.requests == c.limits and c.requests
                 for c in pod.containers):
            qos = 2  # Guaranteed-ish: requests == limits
        else:
            qos = 1  # Burstable
        return (qos, -(usage - request))

    def synchronize(self, admitted: Dict[str, Pod], extra_disk: int = 0,
                    disk_reclaim=None) -> List[str]:
        """Returns pod keys to evict, updating the pressure flags. Evicts
        greedily in rank order until the signal clears, like the manager's
        one-eviction-per-sync loop collapsed into one pass.

        `extra_disk` is non-pod disk usage (the image filesystem);
        `disk_reclaim(bytes)` frees node-level disk (image GC) and returns
        bytes freed — tried BEFORE any pod is evicted, mirroring
        eviction_manager.go reclaimNodeLevelResources."""
        mem_use = disk_use = 0
        per_pod = {}
        for key, pod in admitted.items():
            m, d = self._pod_usage(pod)
            per_pod[key] = (m, d)
            mem_use += m
            disk_use += d
        # static (mirror) pods are exempt, like the manager's critical-pod
        # carve-out (eviction_manager.go; static pods are kubelet-owned and
        # would just be restarted by their source)
        evictable = {k: p for k, p in admitted.items()
                     if MIRROR_ANNOTATION not in p.annotations}
        to_evict: List[str] = []
        self.memory_pressure = self._alloc_mem > 0 \
            and mem_use > self.memory_limit
        if self.memory_pressure:
            ranked = sorted(
                evictable.items(),
                key=lambda kv: self._qos_rank(
                    kv[1], per_pod[kv[0]][0],
                    kv[1].resource_request().memory))
            for key, _pod in ranked:
                if mem_use <= self.memory_limit:
                    break
                to_evict.append(key)
                mem_use -= per_pod[key][0]
        if self.disk_limit:
            disk_use += extra_disk
            self.disk_pressure = disk_use > self.disk_limit
            if self.disk_pressure and disk_reclaim is not None:
                disk_use -= disk_reclaim(disk_use - self.disk_limit)
                self.disk_pressure = disk_use > self.disk_limit
            if self.disk_pressure:
                ranked = sorted(
                    evictable.items(),
                    key=lambda kv: self._qos_rank(
                        kv[1], per_pod[kv[0]][1],
                        kv[1].resource_request().storage_scratch))
                for key, _pod in ranked:
                    if disk_use <= self.disk_limit:
                        break
                    if key not in to_evict:
                        to_evict.append(key)
                        disk_use -= per_pod[key][1]
        return to_evict


class HollowKubelet:
    def __init__(self, api: ApiServerLite, node: Node,
                 startup_latency: float = 0.0,
                 now: Callable[[], float] = time.monotonic,
                 volume_manager=None, checkpointer=None,
                 runtime=None, reserved=None):
        from kubernetes_tpu.nodes.cri import FakeRuntimeService
        from kubernetes_tpu.nodes.images import (
            ImageGCManager,
            ImageManager,
        )
        from kubernetes_tpu.nodes.kuberuntime import RuntimeManager
        self.api = api
        self.node_name = node.name
        self._now = now
        self.startup_latency = startup_latency
        # node-allocatable reservation (--kube-reserved/--system-reserved;
        # pkg/kubelet/cm/node_container_manager.go GetNodeAllocatable
        # Reservation): the node's given resources are its CAPACITY;
        # what registers as allocatable — what the scheduler and the
        # node-side admission see — is capacity minus the reservation
        if reserved is not None:
            import dataclasses as _dc
            cap = node.allocatable
            node = _dc.replace(node, capacity=cap, allocatable=Resource(
                milli_cpu=max(0, cap.milli_cpu - reserved.milli_cpu),
                memory=max(0, cap.memory - reserved.memory),
                nvidia_gpu=max(0, cap.nvidia_gpu - reserved.nvidia_gpu),
                storage_scratch=max(
                    0, cap.storage_scratch - reserved.storage_scratch),
                storage_overlay=max(
                    0, cap.storage_overlay - reserved.storage_overlay),
                extended=dict(cap.extended)))
        self._template = node
        # THE runtime boundary (nodes/cri.py; ref pkg/kubelet/apis/cri/
        # services.go): any RuntimeService+ImageService plugs in here; the
        # default is the scripted fake (the kubemark hollow runtime)
        if runtime is None:
            runtime = FakeRuntimeService(boot_latency=startup_latency,
                                         now=now)
        self.runtime = runtime
        self.images = ImageManager(runtime)
        # image fs capacity = the node's scratch disk (cadvisor
        # ImagesFsInfo in the reference)
        self.image_gc = ImageGCManager(
            runtime, capacity_bytes=node.allocatable.storage_scratch)
        self.runtime_mgr = RuntimeManager(runtime, image_manager=self.images,
                                          now=now)
        # pod keys whose containers are not all Running yet (startup or
        # liveness-restart in flight); the step() loop polls the runtime
        # for them — the PLEG relist analog (pkg/kubelet/pleg/)
        self._starting: Dict[str, float] = {}
        self._admitted: Dict[str, Pod] = {}  # local running set
        self._restarts: Dict[str, int] = {}  # pod key -> restart count
        self._ready: Dict[str, bool] = {}  # last written Ready condition
        self.workers = PodWorkers(self._sync_pod)
        self.prober = ProberManager(now)
        self.eviction = EvictionManager(node)
        self._static: Dict[str, Pod] = {}  # static (mirror-backed) pods
        # volumes/manager.py VolumeManager; None keeps the hollow-fleet
        # fast path volume-free (kubemark's hollow kubelet does the same)
        self.volumes = volume_manager
        # node-local sandbox checkpoints (nodes/checkpoint.py, the
        # dockershim checkpoint_store analog): restart counters survive a
        # kubelet restart instead of resetting to zero
        self.checkpointer = checkpointer
        # restore_all validates + prunes corrupt blobs in one pass and
        # never raises — kubelet startup must survive any checkpoint state
        self._restored: Dict[str, Dict] = {} if checkpointer is None \
            else checkpointer.restore_all()

    # ----------------------------------------------------------- node status

    def register(self) -> None:
        """Initial node registration (kubelet registerWithAPIServer)."""
        node = dataclasses.replace(self._template, heartbeat=self._now())
        try:
            self.api.create("Node", node)
        except Conflict:
            self.heartbeat()

    def heartbeat(self) -> None:
        """syncNodeStatus: bump heartbeat, assert Ready, and report the
        eviction manager's pressure signals as node conditions (the
        kubelet-side source of CheckNodeMemoryPressure/DiskPressure)."""
        try:
            cur: Node = self.api.get("Node", "", self.node_name)
        except NotFound:
            return
        keep = ("Ready", "MemoryPressure", "DiskPressure")
        conds = [c for c in cur.conditions if c.type not in keep]
        conds.append(NodeCondition("Ready", ConditionStatus.TRUE))
        conds.append(NodeCondition(
            "MemoryPressure", ConditionStatus.TRUE
            if self.eviction.memory_pressure else ConditionStatus.FALSE))
        conds.append(NodeCondition(
            "DiskPressure", ConditionStatus.TRUE
            if self.eviction.disk_pressure else ConditionStatus.FALSE))
        ann = dict(cur.annotations)
        if self.volumes is not None:
            # node.status.volumesInUse: the attach-detach controller's
            # detach guard (volume_manager.go GetVolumesInUse)
            from kubernetes_tpu.controllers.cloudctrl import \
                IN_USE_ANNOTATION
            in_use = ",".join(self.volumes.volumes_in_use())
            if in_use:
                ann[IN_USE_ANNOTATION] = in_use
            else:
                ann.pop(IN_USE_ANNOTATION, None)
        self.api.update("Node", dataclasses.replace(
            cur, heartbeat=self._now(), conditions=conds,
            annotations=ann))

    # ------------------------------------------------------------- pod flow

    def _local_usage(self) -> tuple:
        cpu = mem = count = 0
        for p in self._admitted.values():
            r = p.resource_request()
            cpu += r.milli_cpu
            mem += r.memory
            count += 1
        return cpu, mem, count

    def _admit(self, pod: Pod) -> Optional[str]:
        """GeneralPredicates node-side: capacity re-check against local state
        (lifecycle/predicate.go). Returns rejection reason or None."""
        r = pod.resource_request()
        cpu, mem, count = self._local_usage()
        alloc = self._template.allocatable
        if count + 1 > self._template.allowed_pod_number:
            return "OutOfPods"
        if cpu + r.milli_cpu > alloc.milli_cpu:
            return "OutOfcpu"
        if mem + r.memory > alloc.memory:
            return "OutOfmemory"
        return None

    def handle_pod(self, pod: Pod) -> None:
        """A bound pod appeared/changed for this node (syncLoopIteration
        ADD/UPDATE) — enqueued through the per-pod workers."""
        self.workers.update_pod(pod, "sync")

    def _sync_pod(self, pod: Pod, op: str) -> None:
        """The serialized per-pod sync body (kubelet.go:1390 syncPod)."""
        key = pod.key()
        if op == "remove" or pod.phase in ("Succeeded", "Failed"):
            self._forget(key)
            return
        if key in self._admitted or key in self._starting:
            return
        reason = self._admit(pod)
        if reason is not None:
            self._write_status(pod, phase="Failed", reason=reason)
            return
        if self.volumes is not None and pod.volumes:
            # syncPod blocks on WaitForAttachAndMount before containers
            # start (kubelet.go:1390 → volume_manager.go:339); failure
            # leaves the pod Pending for the next sync retry
            from kubernetes_tpu.volumes.plugins import VolumeError
            try:
                # non-blocking (timeout=0): one reconcile attempt per sync
                # pass; a pending attach retries on the next sync instead
                # of stalling the serialized pod workers on wall-clock
                self.volumes.wait_for_attach_and_mount(pod, timeout=0)
            except VolumeError:
                self._write_status(pod, reason="FailedMount")
                return
        self._admitted[key] = pod
        self._starting[key] = self._now()
        # sandbox + image pulls + container create/start, through the CRI
        # boundary (kuberuntime SyncPod); step() polls for Running
        self.runtime_mgr.sync_pod(pod)
        self.prober.add_pod(pod, self._now())
        rec = self._restored.pop(key, None)
        if rec is not None and rec.get("restarts"):
            # resume the pre-restart counter (docker_checkpoint.go's
            # sandbox state reconstruction)
            self._restarts[key] = rec["restarts"]
        self._checkpoint(key)

    def _checkpoint(self, key: str) -> None:
        if self.checkpointer is None:
            return
        self.checkpointer.checkpoint(key, {
            "restarts": self._restarts.get(key, 0),
            "node": self.node_name})

    def forget_pod(self, pod: Pod) -> None:
        """Pod deleted from the apiserver (kubelet HandlePodRemoves)."""
        self.workers.update_pod(pod, "remove")

    def _forget(self, key: str) -> None:
        self._admitted.pop(key, None)
        self._starting.pop(key, None)
        self.runtime_mgr.kill_pod(key)
        self._restarts.pop(key, None)
        self._ready.pop(key, None)
        self.workers.forget(key)
        self.prober.remove_pod(key)
        if self.volumes is not None:
            self.volumes.teardown_pod(key)
        if self.checkpointer is not None:
            self.checkpointer.remove(key)

    # ---------------------------------------------------- kubelet API serving

    def serve_pods(self) -> list:
        """/pods: the admitted pod set (server.go InstallDefaultHandlers).
        dict() snapshot: handler threads must not iterate the live dict
        while the sync loop mutates it."""
        admitted = dict(self._admitted)
        restarts = dict(self._restarts)
        return [{"name": p.name, "namespace": p.namespace,
                 "phase": p.phase, "restartCount": restarts.get(key, 0)}
                for key, p in sorted(admitted.items())]

    def serve_stats(self) -> dict:
        """/stats/summary: the cadvisor summary shape."""
        cpu = mem = count = 0
        for p in dict(self._admitted).values():
            r = p.resource_request()
            cpu += r.milli_cpu
            mem += r.memory
            count += 1
        return {"node": {"nodeName": self.node_name,
                         "cpu": {"usageMilli": cpu},
                         "memory": {"workingSetBytes": mem}},
                "pods": count}

    def serve_logs(self, namespace: str, name: str,
                   tail=None) -> str:
        """/containerLogs/<ns>/<pod>: the one source of truth for the
        hollow log semantics (both the HTTP server and in-process ktctl
        route here). tail=0 prints nothing, like kubectl --tail=0."""
        from kubernetes_tpu.nodes.kubelet_server import (
            KubeletApiError,
            LOG_LINES_ANNOTATION,
        )
        pod = self._admitted.get(namespace + "/" + name)
        if pod is None:
            raise KubeletApiError(
                404, f'pod "{namespace}/{name}" is not running on node '
                     f'"{self.node_name}"')
        lines = pod.annotations.get(LOG_LINES_ANNOTATION, "").split("\n")
        if tail is not None:
            try:
                n = int(tail)
            except (TypeError, ValueError):
                raise KubeletApiError(
                    400, f"invalid tailLines {tail!r}") from None
            lines = lines[-n:] if n > 0 else []
        return "\n".join(lines)

    def serve_attach(self, namespace: str, name: str) -> str:
        """POST /attach/<ns>/<pod>: attach to the running container's
        output stream (server.go InstallDebuggingHandlers attach; the
        hollow stream is the pod's current log tail). Attaching to a pod
        that is not Running is an error, unlike logs."""
        from kubernetes_tpu.nodes.kubelet_server import KubeletApiError
        pod = self._admitted.get(namespace + "/" + name)
        if pod is None or pod.key() in self._starting:
            raise KubeletApiError(
                404, f'cannot attach: pod "{namespace}/{name}" is not '
                     f'running on node "{self.node_name}"')
        return self.serve_logs(namespace, name)

    PORT_ANNOTATION_PREFIX = "bench/port-"

    def serve_port(self, namespace: str, name: str, port: int) -> bytes:
        """GET /portForward/<ns>/<pod>?port=N: one round of the
        port-forward stream — what the pod "serves" on that port (the
        hollow runtime scripts it via the bench/port-<N> annotation, the
        way it scripts exec outputs)."""
        from kubernetes_tpu.nodes.kubelet_server import KubeletApiError
        pod = self._admitted.get(namespace + "/" + name)
        if pod is None:
            raise KubeletApiError(
                404, f'pod "{namespace}/{name}" is not running on node '
                     f'"{self.node_name}"')
        payload = pod.annotations.get(
            self.PORT_ANNOTATION_PREFIX + str(port))
        if payload is None:
            raise KubeletApiError(
                400, f"pod {namespace}/{name} does not serve port {port}")
        return payload.encode()

    def serve_exec(self, namespace: str, name: str, cmd: str) -> str:
        """POST /exec/<ns>/<pod>?command=...: canned hollow outputs."""
        from kubernetes_tpu.nodes.kubelet_server import (
            EXEC_PREFIX_ANNOTATION,
            KubeletApiError,
        )
        pod = self._admitted.get(namespace + "/" + name)
        if pod is None:
            raise KubeletApiError(
                404, f'pod "{namespace}/{name}" is not running on node '
                     f'"{self.node_name}"')
        out = pod.annotations.get(EXEC_PREFIX_ANNOTATION + cmd)
        if out is None:
            raise KubeletApiError(
                501, f"no handler for command {cmd!r} in the hollow "
                     f"runtime")
        return out

    # ----------------------------------------------------------- static pods

    def add_static_pod(self, pod: Pod) -> None:
        """A static-pod manifest (file/HTTP source, pkg/kubelet/config/):
        runs locally without a scheduler and surfaces on the apiserver as a
        MIRROR pod the kubelet owns (kubelet.go mirror-pod handling)."""
        pod = dataclasses.replace(
            pod, node_name=self.node_name,
            annotations={**pod.annotations, MIRROR_ANNOTATION: "true"})
        self._static[pod.key()] = pod
        self.workers.update_pod(pod, "sync")
        self._ensure_mirror(pod)

    def load_static_dir(self, path: str) -> int:
        """Read every *.json manifest in `path` (config/file.go source)."""
        import json
        import os

        from kubernetes_tpu.api import serde
        n = 0
        for fn in sorted(os.listdir(path)):
            if not fn.endswith(".json"):
                continue
            with open(os.path.join(path, fn)) as f:
                self.add_static_pod(serde.decode_pod(json.load(f)))
                n += 1
        return n

    def _ensure_mirror(self, pod: Pod) -> None:
        """Recreate the mirror pod if absent — the apiserver copy is a
        projection the kubelet owns; deleting it does not stop the static
        pod."""
        try:
            self.api.get("Pod", pod.namespace, pod.name)
        except NotFound:
            mirror = dataclasses.replace(pod, resource_version=0)
            try:
                self.api.create("Pod", mirror)
            except Conflict:
                pass

    # ------------------------------------------------------------- sync loop

    def step(self) -> int:
        """One syncLoop iteration: drain the pod workers, advance startups
        and completions, run the probe workers, run the eviction manager.
        Returns number of status transitions written."""
        now = self._now()
        wrote = 0
        self.workers.drain()
        # orphaned-checkpoint GC: a restored record whose pod was deleted
        # (or rebound) while this kubelet was down never gets re-admitted
        # — without this sweep its file lives forever and a future
        # same-named pod would inherit a dead pod's restart counter
        if self.checkpointer is not None and self._restored:
            for pod_key in list(self._restored):
                ns, _, name = pod_key.partition("/")
                try:
                    cur = self.api.get("Pod", ns, name)
                    stale = cur.node_name != self.node_name
                except NotFound:
                    stale = True
                if stale:
                    self._restored.pop(pod_key, None)
                    self.checkpointer.remove(pod_key)
        for pod in self._static.values():
            self._ensure_mirror(pod)
        # ---- runtime relist for in-flight startups (the PLEG pass) ------
        for key in list(self._starting):
            pod = self._admitted.get(key)
            if pod is None:
                del self._starting[key]
                continue
            # one status read; execute actions only when there are any (a
            # liveness restart leaves killed containers behind —
            # computePodActions starts the fresh attempt here)
            status = self.runtime_mgr.pod_status(pod)
            actions = self.runtime_mgr.compute_pod_actions(pod, status)
            if self.runtime_mgr.actions_needed(actions):
                self.runtime_mgr.execute_pod_actions(pod, actions)
                status = self.runtime_mgr.pod_status(pod)
            if not (status.all_running or status.completed_phase):
                continue
            del self._starting[key]
            if status.completed_phase:
                # already terminal at relist time — e.g. a liveness-killed
                # (137) pod whose restartPolicy Never forbids the fresh
                # attempt: report Failed/Succeeded and release it, never
                # "Running" (it would sit unready forever — the scripted
                # completion sweep below only polls annotated workloads)
                self._admitted.pop(key, None)
                if self._write_status(pod, phase=status.completed_phase):
                    wrote += 1
                continue
            # a pod with a readiness probe starts NOT-ready; the probe
            # flips it (results_manager initial state)
            ready0 = not self.prober.has_readiness(key)
            if self._write_status(pod, phase="Running", ready=ready0,
                                  restart_count=self._restarts.get(key, 0)):
                wrote += 1
            self._ready[key] = ready0
        # ---- probe workers over running pods ----------------------------
        for key, pod in list(self._admitted.items()):
            if key in self._starting:
                continue
            ready, live = self.prober.tick(pod)
            if live is False:
                wrote += self._restart_container(key, pod)
                continue
            if ready is not None and ready != self._ready.get(key):
                if self._write_status(pod, ready=ready):
                    wrote += 1
                    self._ready[key] = ready
        # ---- eviction manager -------------------------------------------
        for key in self.eviction.synchronize(
                {k: p for k, p in self._admitted.items()
                 if k not in self._starting},
                extra_disk=self.runtime.image_fs_info(),
                disk_reclaim=self.image_gc.free_space):
            pod = self._admitted.get(key)
            if pod is not None:
                if self._write_status(pod, phase="Failed", reason="Evicted"):
                    wrote += 1
                self._forget(key)
        # ---- run-to-completion: the runtime reports natural exits -------
        for key, pod in list(self._admitted.items()):
            if key in self._starting:
                continue
            # scripted runtime: only pods with a scripted exit can finish;
            # a REAL runtime's containers can die anytime, so poll them all
            if self.runtime.exits_are_scripted \
                    and RUN_SECONDS_ANNOTATION not in pod.annotations:
                continue
            status = self.runtime_mgr.pod_status(pod)
            if not status.completed_phase:
                continue
            self._admitted.pop(key, None)
            if self._write_status(pod, phase=status.completed_phase):
                wrote += 1
        return wrote

    def _restart_container(self, key: str, pod: Pod) -> int:
        """Liveness failure past threshold: restart per restartPolicy
        (kuberuntime SyncPod computePodActions kill+recreate; restartPolicy
        Never -> the pod fails)."""
        if pod.restart_policy == "Never":
            self._write_status(pod, phase="Failed", reason="Unhealthy")
            self._forget(key)
            return 1
        self._restarts[key] = self._restarts.get(key, 0) + 1
        self._checkpoint(key)
        # CRI kill + immediate re-sync: the fresh attempt starts NOW (with
        # the runtime's boot latency), not one step later — keeping restart
        # downtime and the prober's restart clock in agreement
        self.runtime_mgr.restart_pod_containers(pod)
        self.runtime_mgr.sync_pod(pod)
        started_at = self._now() + self.startup_latency
        self._starting[key] = self._now()
        self.prober.restart(pod, started_at)
        wrote = 0
        # pod goes unready while the container restarts
        if self._write_status(pod, ready=False,
                              restart_count=self._restarts[key]):
            self._ready[key] = False
            wrote = 1
        return wrote

    def _write_status(self, pod: Pod, phase: Optional[str] = None,
                      ready: Optional[bool] = None,
                      restart_count: Optional[int] = None,
                      reason: str = "") -> bool:
        """Status-manager PATCH with conflict retry."""
        for _ in range(3):
            try:
                cur: Pod = self.api.get("Pod", pod.namespace, pod.name)
            except NotFound:
                self._forget(pod.key())
                return False
            if cur.node_name != self.node_name:
                return False  # rebound elsewhere
            ann = dict(cur.annotations)
            if reason:
                ann["kubernetes.io/failure-reason"] = reason
            changes = dict(annotations=ann)
            if phase is not None:
                changes["phase"] = phase
            if ready is not None:
                changes["ready"] = ready
            if restart_count is not None:
                changes["restart_count"] = restart_count
            try:
                self.api.update("Pod", dataclasses.replace(cur, **changes),
                                expect_rv=cur.resource_version)
                return True
            except Conflict:
                continue
            except NotFound:
                return False
        return False


class HollowFleet:
    """N hollow kubelets behind ONE pod informer (by-node index dispatch)."""

    def __init__(self, api: ApiServerLite, factory: SharedInformerFactory,
                 startup_latency: float = 0.0,
                 now: Callable[[], float] = time.monotonic):
        self.api = api
        self._now = now
        self.startup_latency = startup_latency
        self.kubelets: Dict[str, HollowKubelet] = {}
        self.pod_informer = factory.informer("Pod")
        self.pod_informer.add_event_handler(
            on_add=self._dispatch_add,
            on_update=self._dispatch_update,
            on_delete=self._dispatch_delete)

    def add_node(self, node: Node, register: bool = True) -> HollowKubelet:
        kl = HollowKubelet(self.api, node,
                           startup_latency=self.startup_latency, now=self._now)
        self.kubelets[node.name] = kl
        if register:
            kl.register()
        return kl

    def _dispatch_add(self, pod: Pod) -> None:
        if pod.node_name and pod.node_name in self.kubelets:
            self.kubelets[pod.node_name].handle_pod(pod)

    def _dispatch_update(self, old: Pod, new: Pod) -> None:
        if old.node_name and old.node_name != new.node_name \
                and old.node_name in self.kubelets:
            self.kubelets[old.node_name].forget_pod(old)
        self._dispatch_add(new)

    def _dispatch_delete(self, pod: Pod) -> None:
        if pod.node_name and pod.node_name in self.kubelets:
            self.kubelets[pod.node_name].forget_pod(pod)

    def step(self) -> int:
        """Advance every kubelet's pod state machines."""
        return sum(kl.step() for kl in self.kubelets.values())

    def heartbeat_all(self) -> None:
        for kl in self.kubelets.values():
            kl.heartbeat()
