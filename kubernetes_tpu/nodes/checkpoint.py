"""Node-local checkpoint store — the dockershim checkpoint analog.

Reference: pkg/kubelet/dockershim/checkpoint_store.go — the only
node-local durable state in the v1.7 tree. `CheckpointStore` is a
key→blob interface (`Write/Read/Delete/List`), `FileStore` the
filesystem implementation with atomic writes and key validation;
dockershim checkpoints pod-sandbox metadata through it so a restarted
kubelet can reconstruct sandbox state (port mappings, host-network
flag) before the runtime is queried
(pkg/kubelet/dockershim/docker_checkpoint.go PodSandboxCheckpoint).

Here the kubelet checkpoints its admitted-pod sandbox records
(restarts, static-pod specs ride their own file source) so a
restarted HollowKubelet resumes restart counters and running state
without replaying every status write — exercised by the chaos
harness's kubelet-kill path.
"""

from __future__ import annotations

import errno
import json
import os
import re
import tempfile
from typing import Any, Dict, List

_KEY_RE = re.compile(r"^[a-zA-Z0-9][a-zA-Z0-9._-]*$")


class CorruptCheckpointError(Exception):
    """Stored blob failed validation on read (checkpoint_store.go's
    data-validation on Read)."""


class CheckpointStore:
    """checkpoint_store.go CheckpointStore interface."""

    def write(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def read(self, key: str) -> bytes:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def list(self) -> List[str]:
        raise NotImplementedError


def validate_key(key: str) -> None:
    """checkpoint_store.go validateKey: keys must be regular filenames —
    no separators/traversal, non-empty."""
    if not key or len(key) > 250 or not _KEY_RE.match(key) \
            or key in (".", ".."):
        raise ValueError(f"checkpoint key is not valid: {key!r}")


class FileStore(CheckpointStore):
    """checkpoint_store.go FileStore: one file per key under a base dir,
    atomic tmp-file + rename writes (util/ioutils atomic write), missing
    key on delete is NOT an error (idempotent cleanup)."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, key: str) -> str:
        validate_key(key)
        return os.path.join(self.directory, key)

    def write(self, key: str, data: bytes) -> None:
        path = self._path(key)
        # truncated prefix: the tmp name must stay under the filesystem's
        # 255-byte filename limit even for maximum-length keys
        fd, tmp = tempfile.mkstemp(dir=self.directory,
                                   prefix=".tmp-" + key[:40] + "-")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    def read(self, key: str) -> bytes:
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise KeyError(key) from None

    def delete(self, key: str) -> None:
        try:
            os.unlink(self._path(key))
        except OSError as e:
            if e.errno != errno.ENOENT:
                raise

    def list(self) -> List[str]:
        return sorted(
            fn for fn in os.listdir(self.directory)
            if not fn.startswith(".tmp-"))


class MemStore(CheckpointStore):
    """The in-memory store used in dockershim tests
    (checkpoint_store.go MemStore)."""

    def __init__(self):
        self._data: Dict[str, bytes] = {}

    def write(self, key: str, data: bytes) -> None:
        validate_key(key)
        self._data[key] = bytes(data)

    def read(self, key: str) -> bytes:
        if key not in self._data:
            raise KeyError(key)
        return self._data[key]

    def delete(self, key: str) -> None:
        validate_key(key)
        self._data.pop(key, None)

    def list(self) -> List[str]:
        return sorted(self._data)


CHECKPOINT_VERSION = "v1"


class PodSandboxCheckpointer:
    """docker_checkpoint.go PodSandboxCheckpoint over a CheckpointStore:
    the kubelet-side record of a pod's sandbox (restart count + phase)
    with a version + checksum envelope, validated on read."""

    def __init__(self, store: CheckpointStore):
        self.store = store

    @staticmethod
    def _key(pod_key: str) -> str:
        # "default/web-1" -> "default_web-1" (keys must be plain
        # filenames). k8s ns+name can reach ~500 chars, past both
        # validate_key's 250 limit and the filesystem's 255 after the
        # atomic-write tmp prefix — long keys get a fixed-width digest
        # suffix so they stay unique AND mountable
        key = pod_key.replace("/", "_")
        if len(key) > 200:
            import hashlib
            key = key[:160] + "-" + hashlib.sha256(
                pod_key.encode()).hexdigest()[:32]
        return key

    def checkpoint(self, pod_key: str, record: Dict[str, Any]) -> None:
        body = {"version": CHECKPOINT_VERSION, "pod": pod_key,
                "record": record}
        payload = json.dumps(body, sort_keys=True)
        body["checksum"] = _checksum(payload)
        self.store.write(self._key(pod_key),
                         json.dumps(body, sort_keys=True).encode())

    def restore(self, pod_key: str) -> Dict[str, Any]:
        raw = self.store.read(self._key(pod_key))
        try:
            body = json.loads(raw)
            checksum = body.pop("checksum")
        except (ValueError, KeyError):
            raise CorruptCheckpointError(
                f"checkpoint for {pod_key!r} is not valid JSON") from None
        if _checksum(json.dumps(body, sort_keys=True)) != checksum \
                or body.get("version") != CHECKPOINT_VERSION:
            raise CorruptCheckpointError(
                f"checkpoint for {pod_key!r} failed checksum/version "
                f"validation")
        return body["record"]

    def remove(self, pod_key: str) -> None:
        self.store.delete(self._key(pod_key))

    def restore_all(self) -> Dict[str, Dict[str, Any]]:
        """One pass over the store: {pod_key: record} for every VALID
        checkpoint; invalid blobs — bad JSON, wrong shape, failed
        checksum — are deleted as found (dockershim removes checkpoints
        that fail validation rather than serving garbage forever)."""
        out: Dict[str, Dict[str, Any]] = {}
        for key in self.store.list():
            try:
                raw = self.store.read(key)
                body = json.loads(raw)
                checksum = body.pop("checksum")
                pod_key = body["pod"]
                if not isinstance(pod_key, str) \
                        or _checksum(json.dumps(body, sort_keys=True)) \
                        != checksum \
                        or body.get("version") != CHECKPOINT_VERSION:
                    raise CorruptCheckpointError(key)
                out[pod_key] = body["record"]
            except Exception:
                # any malformation (non-dict JSON, missing fields, type
                # surprises) means an unusable checkpoint: drop it and
                # start that pod fresh — never crash kubelet startup
                self.store.delete(key)
        return out

    def pod_keys(self) -> List[str]:
        return sorted(self.restore_all())


def _checksum(payload: str) -> int:
    import zlib
    return zlib.adler32(payload.encode())
